"""Counter-based hot-row admission, shared by serving and training.

Production recommender traffic is power-law distributed: a small set of
hot rows absorbs most lookups. Two subsystems exploit that skew with the
SAME admission policy and must not drift:

  * the serving HBM hot-row cache (`serving/cache.py` `HotRowCache`) —
    hot rows of a host-offloaded bucket are served from device memory;
  * the training hot-row shard (`layers/dist_model_parallel.py`,
    `DistributedEmbedding(hot_rows=...)`) — hot rows of a model-parallel
    bucket are replicated data-parallel so hits skip the id exchange and
    the table-scale gather/scatter.

`HotnessTracker` is the factored host-side core both use: per-row access
counters, a bounded-memory pruning rule, a pending set of
threshold-crossers, a fixed-capacity resident set (key -> slot), and the
admission/eviction policy. It never touches device state — callers copy
rows around; the tracker only decides WHICH rows are hot.

Rows are keyed by an opaque non-negative integer (the stacked-bucket
``world_slice * rows_max + local_row`` flat key in both current callers).
"""

from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["HotnessTracker"]


class HotnessTracker:
    """Access counters + admission policy over a fixed-capacity hot set.

    Args:
      capacity: number of resident slots (static).
      promote_threshold: access count at which a row becomes
        promotion-eligible (>= 1; 1 promotes on first touch).
      max_tracked: bound on the counter dict; beyond it, counters prune
        back to the hottest max_tracked/2 keys (plus residents). Default
        max(64 * capacity, 4096).
      decay: optional exponential aging factor in (0, 1]: each observing
        call ages every tracked count by `decay`, so a long-running
        stream's counts estimate recent frequency rather than all-time
        totals (ISSUE 7: streaming admission must follow key-universe
        drift — a key hot an hour ago must eventually lose to a key hot
        now). The steady-state count of a key seen n times per
        observation window converges to n / (1 - decay), so
        promote_threshold keeps its meaning as "sustained recent rate",
        and counts that age below `DECAY_EPSILON` are dropped (the
        aged-out analogue of `_prune_counts`, keeping the dict bounded
        by activity, not history). None (default) keeps the original
        integer all-time counters — bit-identical policy to every
        pre-decay caller.

        Implementation is LAZY: aging never sweeps the dict per batch
        (that would be O(tracked) Python work on every training step —
        unaffordable at production key rates). Counts are stored in
        inflated units (`stored = true * decay**-tick`); an observation
        just bumps the global tick and adds at the current inflation,
        so a single stored value ages implicitly as the tick advances.
        The dict is swept only every `DECAY_SWEEP_EVERY` ticks (aged-out
        eviction, amortized), and stored values renormalize before the
        inflation factor can overflow a double.
    """

    DECAY_EPSILON = 0.5       # aged counts below this stop being tracked
    DECAY_SWEEP_EVERY = 64    # aged-out eviction cadence (amortized)
    _SCALE_RENORM = 1e100     # renormalize stored units before overflow

    def __init__(self, capacity: int, promote_threshold: int = 2,
                 max_tracked: Optional[int] = None,
                 decay: Optional[float] = None):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        if promote_threshold < 1:
            raise ValueError("promote_threshold must be >= 1")
        if decay is not None and not 0.0 < decay <= 1.0:
            raise ValueError(f"decay must be in (0, 1], got {decay}")
        self.capacity = int(capacity)
        self.promote_threshold = int(promote_threshold)
        self.decay = None if decay is None or decay == 1.0 else float(decay)
        self.max_tracked = int(max_tracked or max(64 * capacity, 4096))
        self._index: Dict[int, int] = {}          # row key -> slot
        self.slot_keys = np.full((self.capacity,), -1, np.int64)
        # row key -> access count. With decay, values are in INFLATED
        # units: true_count = stored / _scale, where _scale grows by
        # 1/decay per observing call (lazy aging — see class docstring)
        self._counts: Dict[int, float] = {}
        self._scale = 1.0
        self._ticks_since_sweep = 0
        self._pending: set = set()                # threshold-crossed keys
        # stats (valid lanes only — callers mask padding before observing)
        self.hits = 0
        self.misses = 0
        self.promotions = 0
        self.evictions = 0

    # ------------------------------------------------------------- observe
    def lookup_slots(self, keys: np.ndarray,
                     valid: Optional[np.ndarray] = None,
                     observe: bool = True) -> np.ndarray:
        """Map row keys to resident slots: >= 0 on hit, -1 on miss.

        Args:
          keys: integer array (any shape) of row keys.
          valid: optional same-shape bool mask; invalid lanes (exchange
            padding) always map to -1 and never touch counters or stats.
          observe: update access counters + hit/miss stats (warmup passes
            set False so compile-ahead does not skew admission).

        Returns an int32 array of `keys`' shape.
        """
        flat = np.asarray(keys, np.int64).reshape(-1)
        vmask = (np.ones(flat.shape, bool) if valid is None
                 else np.asarray(valid, bool).reshape(-1))
        out = np.full(flat.shape, -1, np.int32)
        if observe and self.decay is not None:
            self._tick_decay()
        pthr = self.promote_threshold * self._scale
        uniq, inv, counts = np.unique(flat[vmask], return_inverse=True,
                                      return_counts=True)
        slot_of = np.full(uniq.shape, -1, np.int32)
        for u, key in enumerate(uniq.tolist()):
            s = self._index.get(key)
            if s is not None:
                slot_of[u] = s
            if observe:
                # stored units are inflated by _scale (lazy decay); with
                # decay off, _scale stays 1.0 and these are the original
                # integer counters
                inc = (int(counts[u]) if self.decay is None
                       else counts[u] * self._scale)
                c = self._counts.get(key, 0) + inc
                self._counts[key] = c
                if s is None and c >= pthr:
                    self._pending.add(key)
        if observe and len(self._counts) > self.max_tracked:
            self._prune_counts()
        out[vmask] = slot_of[inv]
        if observe:
            n_hit = int((out[vmask] >= 0).sum())
            self.hits += n_hit
            self.misses += int(vmask.sum()) - n_hit
        return out.reshape(np.asarray(keys).shape)

    def observe(self, keys: np.ndarray,
                valid: Optional[np.ndarray] = None) -> None:
        """Count-only observation (the training warmup scan's form)."""
        self.lookup_slots(keys, valid=valid, observe=True)

    def _tick_decay(self) -> None:
        """One lazy aging tick: the inflation factor advances (every
        stored count is now implicitly `decay` smaller in true units —
        no dict traversal); periodically (DECAY_SWEEP_EVERY ticks, and
        whenever the factor nears double overflow) the dict is swept:
        stored values renormalize to the fresh scale, counts aged below
        DECAY_EPSILON leave (resident keys stay — the eviction policy
        must always be able to rank them), and pending keys whose aged
        count fell back under the threshold lose their eligibility."""
        self._scale /= self.decay
        self._ticks_since_sweep += 1
        if (self._ticks_since_sweep < self.DECAY_SWEEP_EVERY
                and self._scale <= self._SCALE_RENORM):
            return
        self._ticks_since_sweep = 0
        inv = 1.0 / self._scale
        resident = self._index
        kept = {}
        for k, c in self._counts.items():
            c *= inv                       # back to true units
            if c >= self.DECAY_EPSILON or k in resident:
                kept[k] = c
        self._counts = kept
        self._scale = 1.0
        if self._pending:
            self._pending = {k for k in self._pending
                             if kept.get(k, 0.0) >= self.promote_threshold}

    def _prune_counts(self) -> None:
        """Bound the counter dict: keep resident keys plus the hottest
        half of max_tracked; everything colder restarts from zero if seen
        again (an admissible information loss — a pruned key was, by
        construction, colder than max_tracked/2 other keys)."""
        resident = set(self._index)
        keep_n = self.max_tracked // 2
        hottest = sorted(self._counts.items(), key=lambda kv: -kv[1])[:keep_n]
        kept = {k: c for k, c in hottest}
        for k in resident:
            if k in self._counts:
                kept[k] = self._counts[k]
        self._counts = kept
        self._pending &= set(kept)

    # ----------------------------------------------------------- admission
    def _promotion_candidates(self) -> List[Tuple[float, int]]:
        """Uncached keys whose count crossed the threshold, hottest first
        — drawn from the `_pending` set, not a full counter scan.
        Returned counts are TRUE (de-inflated) units; pending keys whose
        count aged back under the threshold are lazily demoted here."""
        self._pending -= set(self._index)
        if self.decay is not None and self._pending:
            pthr = self.promote_threshold * self._scale
            self._pending = {k for k in self._pending
                             if self._counts.get(k, 0.0) >= pthr}
        inv = 1.0 / self._scale
        cands = [(self._counts.get(k, 0) * inv, k) for k in self._pending]
        cands.sort(reverse=True)
        return cands

    def pending_candidates(self) -> List[Tuple[float, int]]:
        """The (count, key) promotion candidates, hottest first — the
        `plan_admissions` input exposed for callers that own slot
        assignment themselves (the vocab manager binds keys through the
        erasable IntegerLookup rather than this tracker's slot table).
        Does not mutate pending; pair with `drop_pending` once bound."""
        return self._promotion_candidates()

    def drop_pending(self, keys) -> None:
        """Remove keys from the pending set (caller admitted or rejected
        them through its own binding structure)."""
        self._pending -= {int(k) for k in np.asarray(keys).reshape(-1)}

    def counts_for(self, keys) -> np.ndarray:
        """Tracked (possibly decayed) counts for `keys` ([n] float64,
        0 for untracked, TRUE units) — the eviction policy's coldness
        ranking."""
        flat = np.asarray(keys, np.int64).reshape(-1)
        inv = 1.0 / self._scale
        return np.asarray([self._counts.get(int(k), 0.0) * inv
                           for k in flat], np.float64)

    def plan_admissions(self) -> List[Tuple[int, int]]:
        """Run the admission policy against the current counters.

        Returns the (slot, key) assignment plan, hottest first. Free slots
        fill first; when full, a candidate evicts the coldest resident row
        only if the candidate's count is strictly higher. The plan updates
        `slot_keys` (and pops evicted keys from the index, counting
        `evictions`) immediately so a second plan in the same round sees
        the new occupancy; callers copy the planned rows, then call
        `commit_admissions(plan)` to make them resident.
        """
        cands = self._promotion_candidates()
        if not cands:
            return []
        free = [s for s in range(self.capacity) if self.slot_keys[s] < 0]
        plan: List[Tuple[int, int]] = []
        for count, key in cands:
            if free:
                slot = free.pop()
            else:
                # full: evict the coldest resident only for a strictly
                # hotter row. Slots planned earlier this round already
                # carry their NEW key, so the scan ranks them by the
                # newcomer's count, never as empty.
                coldest = min(range(self.capacity),
                              key=lambda s: self._counts.get(
                                  int(self.slot_keys[s]), 0))
                cold_key = int(self.slot_keys[coldest])
                # candidate counts are true units, stored are inflated
                if count <= self._counts.get(cold_key, 0) / self._scale:
                    break                          # sorted: nothing hotter left
                self._index.pop(cold_key, None)
                self.evictions += 1
                slot = coldest
            self.slot_keys[slot] = key
            plan.append((slot, key))
        return plan

    def commit_admissions(self, plan: List[Tuple[int, int]]) -> int:
        """Make a `plan_admissions` plan resident (caller copied the rows).
        Returns rows promoted."""
        for slot, key in plan:
            self._index[key] = slot
            self._pending.discard(key)
        self.promotions += len(plan)
        return len(plan)

    def set_resident(self, keys: np.ndarray) -> None:
        """Replace the resident set wholesale (planner-driven admission,
        e.g. top-H from IntegerLookup counts): key i occupies slot i.
        Evicted keys are not counted as evictions — this is a reset, not
        the online policy."""
        keys = np.asarray(keys, np.int64).reshape(-1)
        if len(keys) > self.capacity:
            raise ValueError(
                f"{len(keys)} keys exceed capacity {self.capacity}")
        if len(np.unique(keys)) != len(keys):
            raise ValueError("resident keys must be unique")
        self._index = {int(k): i for i, k in enumerate(keys.tolist())}
        self.slot_keys.fill(-1)
        self.slot_keys[:len(keys)] = keys
        self._pending -= set(self._index)

    def invalidate(self) -> None:
        """Drop every resident row (hits resume only after re-admission)."""
        pthr = self.promote_threshold * self._scale
        for k in self._index:
            if self._counts.get(k, 0) >= pthr:
                self._pending.add(k)       # still hot: re-promotable
        self._index.clear()
        self.slot_keys.fill(-1)

    def resident_keys(self) -> np.ndarray:
        """Current resident keys ([R] int64, slot order, R <= capacity)."""
        return self.slot_keys[self.slot_keys >= 0].copy()

    def top_keys(self, n: Optional[int] = None) -> np.ndarray:
        """The hottest n tracked keys by count (default: capacity) —
        the 'warmup scan' admission input: observe batches, then
        ``set_resident(top_keys())``."""
        n = self.capacity if n is None else int(n)
        items = sorted(self._counts.items(), key=lambda kv: (-kv[1], kv[0]))
        return np.asarray([k for k, _ in items[:n]], np.int64)

    # ---------------------------------------------------------------- stats
    def reset_stats(self) -> None:
        """Zero the hit/miss counters (NOT the frequency counters or the
        resident set) — callers window measured hit rates to a residency
        epoch, e.g. the training hot shard resets at each re-admission so
        reported rates describe the CURRENT hot set, not the all-miss
        warmup stream."""
        self.hits = 0
        self.misses = 0

    @property
    def resident(self) -> int:
        return int((self.slot_keys >= 0).sum())

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def stats(self) -> dict:
        return {"capacity": self.capacity, "resident": self.resident,
                "hits": self.hits, "misses": self.misses,
                "hit_rate": round(self.hit_rate, 4),
                "promotions": self.promotions, "evictions": self.evictions,
                "tracked": len(self._counts), "pending": len(self._pending)}
