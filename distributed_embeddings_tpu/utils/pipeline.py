"""Bounded multi-stage background ingestion pipeline.

The reference hides host-side input cost behind device compute with a
1-thread prefetch executor (reference examples/dlrm/utils.py:231-254); the
seed's `utils/prefetch.py` kept only the staging half of that overlap — the
`stage()` call runs in the consumer thread, so pread, hash lookup and numpy
batch assembly all still serialize with the train step. This module is the
full overlap: every ingestion stage (read → preprocess → stage) runs in its
own persistent worker thread connected by bounded queues, so steady-state
end-to-end throughput is set by the SLOWEST stage, not the SUM of stages
(docs/perf_model.md "Ingestion pipeline").

Contract highlights:
  * Order-preserving: one worker per stage, FIFO queues — pipelined output
    is bit-identical to serial iteration (tests/test_pipeline.py).
  * Backpressure: every inter-stage queue is bounded by `depth`, so at most
    ``(stages + 1) * depth + stages`` batches are ever materialized.
  * Failure propagation: a worker exception rides the queue BEHIND the
    items already produced — the consumer drains those, then the original
    exception re-raises at the call site (no hang, no silent drop).
  * Clean shutdown: `close()` (or exhaustion, or the context manager) stops
    and joins every worker; no threads leak across pipeline lifetimes.
  * Accounting: per-stage wall time lands in an
    ``ingest/stage_seconds{stage=...}`` histogram family of an
    `obs.MetricRegistry` (`stage_summaries()` reads them; pass
    ``registry=`` to land them in a shared run registry — ISSUE 11), and
    each stage body runs under a `utils.profiling.annotate` region so
    profiler traces show where ingestion time goes.
"""

import queue as queue_lib
import threading
import time
from typing import Any, Callable, Iterable, Optional, Sequence, Tuple

from distributed_embeddings_tpu import faults
from distributed_embeddings_tpu.obs.registry import MetricRegistry

# transient stage-body errors (OSError — real filesystem flakes and the
# injected ``ingest.stage`` fault alike) retry in place this many times
# before propagating through the normal drain-then-raise path; stage fns
# are pure per-item transforms by contract, so a retry is safe
_STAGE_RETRIES = 3

__all__ = ["IngestPipeline", "SerialPipeline", "READ_STAGE"]

# name of the implicit first stage (pulling the source iterator); the
# source's own work — pread, batch synthesis — is accounted here
READ_STAGE = "read"

_END = object()          # end-of-stream sentinel


class _Failure:
    """A worker exception in transit to the consumer (rides the FIFO queue
    behind the items produced before it, preserving drain order)."""

    __slots__ = ("exc", "stage")

    def __init__(self, exc: BaseException, stage: str):
        self.exc = exc
        self.stage = stage


def _annotate(name: str):
    """profiling.annotate, tolerating backends with no profiler configured.

    Delegates to `obs.spans.annotation`, whose works/doesn't-work probe
    is cached process-wide — a profiler-less backend pays ONE failed
    construction total, not one raised-and-swallowed exception per stage
    invocation on every batch (measurable overhead at ingest rates)."""
    from distributed_embeddings_tpu.obs.spans import annotation
    return annotation(f"ingest/{name}")


class IngestPipeline:
    """Background ingestion: stages run ahead of the consumer in threads.

    Args:
      source: iterable of batches (each item is whatever the first stage
        consumes — raw buffers, numpy pytrees, ...). Pulled by a persistent
        reader thread; `next(source)` time is accounted as the ``read``
        stage.
      stages: sequence of ``(name, fn)`` — each fn maps one item to the
        next representation (e.g. ``("preprocess", ds.preprocess)``,
        ``("stage", lambda b: stage_dp_batch(mesh, b))``). One persistent
        worker thread per stage, applied in order.
      depth: bound of every inter-stage queue (2 = classic double buffer).
        Total in-flight batches are capped at
        ``(len(stages) + 1) * depth + len(stages)``.
      name: thread-name prefix (useful in py-spy / faulthandler dumps).
      registry: optional `obs.MetricRegistry` the per-stage histograms
        are created in, as ``ingest/stage_seconds{stage=...}`` families
        (ISSUE 11 — `training.fit` passes its run registry so ingest
        timing lands in the unified snapshot). Default: a private
        registry, preserving per-instance accounting; each stage's
        histogram has exactly one writer thread either way.

    Iterate it like any iterator; `close()` is called automatically on
    exhaustion and on `with` exit, and is idempotent. A worker exception
    surfaces at the consumer as the original exception after the items
    staged before it have been drained.
    """

    def __init__(self, source: Iterable, stages: Sequence[Tuple[str, Callable]],
                 depth: int = 2, name: str = "ingest",
                 registry: Optional[MetricRegistry] = None):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._stages = [(str(n), fn) for n, fn in stages]
        names = [READ_STAGE] + [n for n, _ in self._stages]
        if len(set(names)) != len(names):
            raise ValueError(f"stage names must be unique (and not "
                             f"{READ_STAGE!r}): {names}")
        self._source = iter(source)
        self._depth = int(depth)
        self._stop = threading.Event()
        self._closed = False
        reg = registry if registry is not None else MetricRegistry()
        self._registry = reg
        self._hists = {n: reg.histogram("ingest/stage_seconds", stage=n)
                       for n in names}
        # queues[0] feeds stage 0; queues[-1] feeds the consumer
        self._queues = [queue_lib.Queue(maxsize=self._depth)
                        for _ in range(len(self._stages) + 1)]
        self._threads = [threading.Thread(
            target=self._read_loop, name=f"{name}-{READ_STAGE}", daemon=True)]
        for i, (sname, fn) in enumerate(self._stages):
            self._threads.append(threading.Thread(
                target=self._stage_loop, args=(i, sname, fn),
                name=f"{name}-{sname}", daemon=True))
        for t in self._threads:
            t.start()

    # ------------------------------------------------------------ workers
    def _put(self, q: queue_lib.Queue, item) -> bool:
        """Bounded put that stays responsive to shutdown. Returns False when
        the pipeline stopped before the item could be enqueued."""
        while not self._stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue_lib.Full:
                continue
        return False

    def _get(self, q: queue_lib.Queue):
        while not self._stop.is_set():
            try:
                return q.get(timeout=0.05)
            except queue_lib.Empty:
                continue
        return _END

    def _read_loop(self):
        hist = self._hists[READ_STAGE]
        out = self._queues[0]
        while True:
            t0 = time.perf_counter()
            try:
                with _annotate(READ_STAGE):
                    item = next(self._source)
            except StopIteration:
                self._put(out, _END)
                return
            except BaseException as e:  # noqa: BLE001 - propagate, never hang
                self._put(out, _Failure(e, READ_STAGE))
                return
            hist.record(time.perf_counter() - t0)
            if not self._put(out, item):
                return

    def _run_stage_body(self, sname: str, fn: Callable, item):
        """One stage application with bounded transient retry (ISSUE 13):
        an `OSError` from the stage body — the ``ingest.stage`` fault
        point injects exactly this class — retries in place up to
        `_STAGE_RETRIES` times (tiny capped backoff, counted in
        ``ingest/stage_retries_total{stage=}``) before propagating, so a
        filesystem flake degrades to a latency blip instead of killing
        the training run. Non-OSError exceptions propagate immediately
        (the drain-then-raise contract is unchanged)."""
        for attempt in range(_STAGE_RETRIES + 1):
            try:
                faults.check_raise("ingest.stage", stage=sname)
                with _annotate(sname):
                    return fn(item)
            except OSError:
                if attempt >= _STAGE_RETRIES:
                    raise
                self._registry.counter("ingest/stage_retries_total",
                                       stage=sname).inc()
                time.sleep(min(0.002 * (2 ** attempt), 0.02))

    def _stage_loop(self, idx: int, sname: str, fn: Callable):
        hist = self._hists[sname]
        inq, outq = self._queues[idx], self._queues[idx + 1]
        while True:
            item = self._get(inq)
            if item is _END:
                self._put(outq, _END)
                return
            if isinstance(item, _Failure):
                self._put(outq, item)
                return
            t0 = time.perf_counter()
            try:
                item = self._run_stage_body(sname, fn, item)
            except BaseException as e:  # noqa: BLE001 - propagate, never hang
                self._put(outq, _Failure(e, sname))
                return
            hist.record(time.perf_counter() - t0)
            if not self._put(outq, item):
                return

    # ----------------------------------------------------------- consumer
    def __iter__(self):
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        outq = self._queues[-1]
        while True:
            try:
                item = outq.get(timeout=0.1)
                break
            except queue_lib.Empty:
                if self._stop.is_set():
                    raise StopIteration from None
                if not self._threads[-1].is_alive() and outq.empty():
                    # final worker died without a sentinel (should be
                    # impossible — every exit path enqueues one); fail
                    # loudly rather than spin forever
                    self.close()
                    raise RuntimeError(
                        "ingestion worker exited without result") from None
        if item is _END:
            self.close()
            raise StopIteration
        if isinstance(item, _Failure):
            self.close()
            raise item.exc
        return item

    # ---------------------------------------------------------- lifecycle
    def close(self):
        """Stop and join all workers; idempotent, never raises on re-entry.

        Safe to call with items still in flight (the bounded queues are
        drained so blocked putters wake up and exit)."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        # drain so workers blocked on put() observe the stop promptly
        for q in self._queues:
            try:
                while True:
                    q.get_nowait()
            except queue_lib.Empty:
                pass
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = [t for t in self._threads if t.is_alive()]
        if self._threads:  # pragma: no cover - blocking source
            # a reader stuck inside next(source) cannot observe the stop
            # event; the workers are daemons, so abandoning them is safe —
            # and close() runs in finally blocks where raising would
            # clobber the caller's result (or mask the real exception)
            import warnings
            warnings.warn(
                "ingestion workers still blocked at close "
                f"({[t.name for t in self._threads]}); abandoning daemon "
                "threads (source iterator blocked in next()?)",
                RuntimeWarning, stacklevel=2)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass

    # --------------------------------------------------------- accounting
    def stage_summaries(self) -> dict:
        """Per-stage wall-time summaries: {stage: {count, mean_ms, p50_ms,
        p95_ms, p99_ms, max_ms}} — `read` is the implicit source stage."""
        return {n: h.summary() for n, h in self._hists.items()}

    def stage_histograms(self) -> dict:
        """The live per-stage `LatencyHistogram` objects — callers that
        aggregate across runs (`LatencyHistogram.merge`, e.g. bench reps)
        read these rather than the summarized dicts."""
        return dict(self._hists)

    def bottleneck(self) -> Optional[str]:
        """Name of the slowest stage by mean wall time (None before any
        item completed) — the stage whose rate bounds pipelined throughput."""
        means = {n: h.summary()["mean_ms"] for n, h in self._hists.items()
                 if h.count}
        return max(means, key=means.get) if means else None


class SerialPipeline:
    """The same stages run inline in the consumer thread, with the same
    per-stage accounting — the baseline arm of `bench.py --mode ingest`
    and the parity reference for tests (pipelined output must be
    bit-identical to this iteration order)."""

    def __init__(self, source: Iterable, stages: Sequence[Tuple[str, Callable]],
                 registry: Optional[MetricRegistry] = None):
        self._source = iter(source)
        self._stages = [(str(n), fn) for n, fn in stages]
        reg = registry if registry is not None else MetricRegistry()
        self._hists = {n: reg.histogram("ingest/stage_seconds", stage=n)
                       for n in [READ_STAGE] + [n for n, _ in self._stages]}

    def __iter__(self):
        return self

    def __next__(self):
        t0 = time.perf_counter()
        item = next(self._source)
        self._hists[READ_STAGE].record(time.perf_counter() - t0)
        for sname, fn in self._stages:
            t0 = time.perf_counter()
            item = fn(item)
            self._hists[sname].record(time.perf_counter() - t0)
        return item

    def close(self):
        pass

    def stage_summaries(self) -> dict:
        return {n: h.summary() for n, h in self._hists.items()}

    def stage_histograms(self) -> dict:
        return dict(self._hists)


def staged_batches(data: Iterable, stage: Optional[Callable] = None,
                   preprocess: Optional[Callable] = None, depth: int = 2,
                   pipelined: bool = True,
                   registry: Optional[MetricRegistry] = None) -> Any:
    """Convenience constructor for the common train-loop shape.

    Args:
      data: iterable of batches.
      stage: device staging fn (default `jax.device_put`) — e.g.
        ``lambda b: stage_dp_batch(mesh, b)``.
      preprocess: optional host transform run in its own worker between
        read and stage (e.g. `RawBinaryDataset.preprocess`, or an
        IntegerLookup translation).
      depth: per-queue bound.
      pipelined: False returns the serial (inline) form with identical
        output — the A/B switch `training.fit(pipelined=...)` exposes.
      registry: optional `obs.MetricRegistry` for the per-stage
        histograms (see `IngestPipeline`).
    """
    import jax
    stages = []
    if preprocess is not None:
        stages.append(("preprocess", preprocess))
    stages.append(("stage", stage or jax.device_put))
    if pipelined:
        return IngestPipeline(data, stages, depth=depth, registry=registry)
    return SerialPipeline(data, stages, registry=registry)
