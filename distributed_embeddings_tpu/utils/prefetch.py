"""Device-prefetching batch iterator.

The reference's data path overlaps host reads with device compute via a
1-thread prefetch executor (reference examples/dlrm/utils.py:231-254). The
TPU-side half of that overlap is staging the NEXT batch into device memory
while the current step runs — jax dispatch is async, so simply keeping a
small queue of already-device_put batches ahead of the consumer hides the
host->HBM transfer entirely.

NOTE: `stage` (and the upstream `next()`) run in the CONSUMER thread — this
iterator hides only the host->device copy behind async dispatch, not the
host-side read/preprocess cost. For full overlap (read, preprocess and
staging each in their own worker thread), use `utils.pipeline.IngestPipeline`
— `training.fit` does, by default.
"""

from collections import deque
from typing import Any, Callable, Iterable, Iterator, Optional

import jax

__all__ = ["prefetch_to_device"]


def prefetch_to_device(batches: Iterable, size: int = 2,
                       stage: Optional[Callable[[Any], Any]] = None
                       ) -> Iterator:
    """Yield batches with `size` of them already staged ahead on device.

    Args:
      batches: iterable of pytrees (numpy or jax arrays).
      size: how many batches to keep in flight (2 = classic double buffer).
      stage: optional per-batch staging function — e.g.
        ``lambda b: stage_dp_batch(mesh, b)`` for multi-process sharded
        inputs, or a `jax.device_put` with a NamedSharding. Defaults to
        `jax.device_put` (committed default-device placement).

    Yields the staged pytrees in order. On an upstream iterator (or stage)
    error, the batches already staged are yielded FIRST and the original
    exception re-raises after the drain — deterministic tail behavior: no
    staged work is silently dropped, and the consumer sees every batch that
    preceded the failure exactly once.
    """
    stage = stage or jax.device_put
    queue: deque = deque()
    it = iter(batches)
    pending_exc = None

    def pull() -> bool:
        nonlocal pending_exc
        if pending_exc is not None:
            return False
        try:
            queue.append(stage(next(it)))
            return True
        except StopIteration:
            return False
        except Exception as e:  # noqa: BLE001 - re-raised after the drain
            pending_exc = e
            return False

    while len(queue) < size and pull():
        pass
    while queue:
        out = queue.popleft()
        pull()
        yield out
    if pending_exc is not None:
        raise pending_exc
