"""Training integration: the reference's Horovod-patch layer, re-thought SPMD.

The reference ships four Horovod integration shims
(reference: distributed_embeddings/python/layers/dist_model_parallel.py:1217-1326):

  * ``DistributedGradientTape`` (:1242) — patches Horovod's tape so
    model-parallel variables (tagged ``var.de_local``) are excluded from the
    allreduce while data-parallel grads are averaged.
  * ``DistributedOptimizer`` (:1270) — same patch for the Keras-fit path.
  * ``broadcast_variables`` (:1219) — initial DP weight sync that skips MP vars.
  * ``BroadcastGlobalVariablesCallback`` (:1303) — Keras callback form.

Under SPMD none of the patching is load-bearing: a jit-compiled train step
over a Mesh computes gradients that automatically follow parameter shardings
(MP-sharded grads stay device-local; replicated-param grads are psummed by the
shard_map/pjit transpose), and every process builds identical initial weights
from the same seed. The behavioral contract — "MP gradients never cross
workers, DP gradients are averaged, one backward pass handles both" (:1242-1267)
— is a property of sharded autodiff here, not of a wrapper.

These classes therefore exist for API parity and for the places where a real
action remains (multi-process weight sync from process-local state, gradient
postprocessing hooks). They are thin, documented, and jit-compatible.
"""

import os
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from distributed_embeddings_tpu.layers.dist_model_parallel import (
    broadcast_variables)
from distributed_embeddings_tpu.ops.sparse_update import (
    drain_sparse_apply, make_sparse_optimizer, prevalidate_active_impl)

__all__ = [
    "DistributedGradientTape",
    "DistributedOptimizer",
    "BroadcastGlobalVariablesCallback",
    "broadcast_variables",
    "make_train_step",
    "make_sparse_train_step",
    "fit",
    "evaluate",
]


class DistributedGradientTape:
    """API-parity shim for the reference DistributedGradientTape (:1242).

    Usage: ``tape = DistributedGradientTape(); loss, grads =
    tape.gradient(loss_fn, params, *args)``. The heavy lifting the reference
    wrapper did (allreduce DP grads, keep MP grads local, sparse_as_dense) is
    inherent to sharded autodiff — grads follow param shardings.
    """

    def __init__(self, sparse_as_dense: bool = True):
        # sparse_as_dense is vacuous: XLA grads of gather are dense
        # scatter-adds already (no IndexedSlices analogue in JAX).
        del sparse_as_dense

    def gradient(self, loss_fn: Callable, params, *args, **kwargs):
        return jax.value_and_grad(loss_fn)(params, *args, **kwargs)


class DistributedOptimizer:
    """Optax wrapper with the reference DistributedOptimizer API (:1270).

    ``init``/``update`` pass through to the wrapped optax optimizer; no
    gradient communication is inserted because none is needed (see module
    docstring). Keeps a hook point (``postprocess``) mirroring the
    reference's gradient-postprocess ability.
    """

    def __init__(self, optimizer,
                 postprocess: Optional[Callable[[Any], Any]] = None):
        self._opt = optimizer
        self._postprocess = postprocess

    def init(self, params):
        return self._opt.init(params)

    def update(self, grads, opt_state, params=None):
        if self._postprocess is not None:
            grads = self._postprocess(grads)
        return self._opt.update(grads, opt_state, params)

    def apply(self, params, updates):
        return apply_updates(params, updates)


class BroadcastGlobalVariablesCallback:
    """API-parity shim for the reference Keras callback (:1303).

    Under SPMD the initial weights are already identical (same program, same
    seed). For multi-process runs restoring from process-local state, call
    ``on_train_begin(params)`` to broadcast from process 0.
    """

    def __init__(self, root_rank: int = 0):
        if root_rank != 0:
            raise NotImplementedError(
                "broadcast_one_to_all always originates from process 0; "
                "root_rank != 0 is not supported")
        self.root_rank = root_rank
        self._done = False

    def on_train_begin(self, params):
        if self._done:
            return params
        self._done = True
        return broadcast_variables(params, root_rank=self.root_rank)


def apply_updates(params, updates):
    """params + updates (optax convention). Delegates to optax.apply_updates,
    which also handles None update leaves (masked optimizers) and casts
    updates to each param's dtype."""
    import optax
    return optax.apply_updates(params, updates)


def default_donate() -> bool:
    """Default for the train-step factories' ``donate`` argument:
    ``DET_STEP_DONATE`` (unset/'1' -> True). The escape hatch exists for
    environments where donated executables cannot be trusted end to end —
    tests/conftest.py sets '0' because jaxlib 0.4.36 XLA:CPU intermittently
    mis-executes DONATED executables loaded from the persistent
    compilation cache (see compat.install_cpu_donation_cache_guard);
    undonated steps are numerically identical, they just update out of
    place."""
    return os.environ.get("DET_STEP_DONATE", "1") != "0"


def make_train_step(loss_fn: Callable, optimizer,
                    donate: Optional[bool] = None,
                    param_shardings: Any = None):
    """Build the canonical jitted SPMD train step.

    Args:
      loss_fn: (params, *batch) -> scalar loss (mean over the global batch —
        this is what makes replicated-param grads come out averaged, the
        reference's hvd.allreduce(average) semantics :1260).
      optimizer: optax optimizer (or DistributedOptimizer).
      donate: donate params/opt_state buffers (in-place update on TPU);
        None defers to `default_donate()` (the DET_STEP_DONATE default).
      param_shardings: optional full params-tree sharding pytree, pinned on
        the step's params output (keeps placement stable across steps).

    Returns:
      step(params, opt_state, *batch) -> (params, opt_state, loss), jitted.
    """
    def step(params, opt_state, *batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, *batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = apply_updates(params, updates)
        return params, opt_state, loss

    if donate is None:
        donate = default_donate()
    donate_argnums = (0, 1) if donate else ()
    out_shardings = ((param_shardings, None, None)
                     if param_shardings is not None else None)
    return jax.jit(step, donate_argnums=donate_argnums,
                   out_shardings=out_shardings)


def _dense_part(params):
    """The densely-trained subtree: everything except the tp/row tables."""
    emb = params["embedding"]
    rest = {k: v for k, v in params.items() if k != "embedding"}
    return {**rest, "embedding": {"dp": emb["dp"]}}


def _merge_dense(dense, params):
    emb = dict(params["embedding"])
    emb["dp"] = dense["embedding"]["dp"]
    out = {k: v for k, v in dense.items() if k != "embedding"}
    out["embedding"] = emb
    return out


def _sparse_optimizer_setup(optimizer: str, lr, strategy: str,
                            dense_optimizer, widths=None):
    """Sparse + dense optimizer construction shared by the monolithic
    step (`make_sparse_train_step`) and the lookahead engine
    (`schedule.LookaheadEngine`) — ONE home for the eps parity
    constants, the kernel prevalidation, and the scheduled-lr per-step
    rebuild rule; the engine's bit-exact-vs-monolithic contract depends
    on these matching exactly.

    Returns ``(scheduled, sopt_for, dense_optimizer)``:
    ``sopt_for(None)`` is the static optimizer (lr 0.0 under a
    schedule); ``sopt_for(opt_state)`` rebuilds it at
    ``lr(opt_state["count"])`` inside the traced step when `lr` is a
    schedule callable, and returns the static one otherwise."""
    import optax

    # eps matches optax's adagrad so dp tables and tp/row tables see the
    # same rule (reference: one Keras optimizer instance for the whole
    # model)
    sparse_hp = {"adagrad": {"eps": 1e-7}, "adam": {}, "sgd": {}}[optimizer]
    scheduled = callable(lr)
    # eagerly validate any DET_SCATTER_IMPL kernel choice on the attached
    # chip now — inside the traced step only the cached verdict is
    # consulted, so without this call the env knob would be silently inert
    prevalidate_active_impl(strategy=strategy, widths=widths)
    sopt = make_sparse_optimizer(optimizer, 0.0 if scheduled else lr,
                                 strategy=strategy, **sparse_hp)
    if dense_optimizer is None:
        dense_optimizer = {
            "sgd": lambda: optax.sgd(lr),
            "adagrad": lambda: optax.adagrad(lr),
            "adam": lambda: optax.adam(lr),
        }[optimizer]()

    def sopt_for(opt_state=None):
        if not scheduled or opt_state is None:
            return sopt
        return make_sparse_optimizer(optimizer, lr(opt_state["count"]),
                                     strategy=strategy, **sparse_hp)

    return scheduled, sopt_for, dense_optimizer


def make_sparse_train_step(model, optimizer: str = "adagrad", lr=0.01,
                           dense_optimizer=None, strategy: str = "auto",
                           donate: Optional[bool] = None,
                           fold_sort: bool = True):
    """Build a train step whose embedding-table updates are row-wise sparse.

    This is the TPU-native analogue of the reference's full sparse training
    path: custom backward emitting (unique_ids, grads)
    (embedding_lookup_kernels.cu:603-775) consumed by the TF optimizer's
    sparse apply. Plain `jax.grad` + optax would materialize a dense [V, w]
    gradient per table and run a full-table optimizer pass per step — O(vocab)
    HBM traffic and memory that caps out far below the reference. Here the
    embedding forward is "tapped" (see DistributedEmbedding.apply taps);
    the backward delivers per-device output gradients, and
    DistributedEmbedding.sparse_update applies O(batch x hotness) row updates
    in place.

    Args:
      model: exposes `.embedding` (DistributedEmbedding) and
        `loss_fn(params, numerical, cats, labels, taps=, return_residuals=)`.
      optimizer: 'sgd' | 'adagrad' | 'adam' — applied sparsely to tp/row
        tables and densely (optax) to everything else.
      lr: learning rate — a scalar, or a schedule callable step -> lr
        (applied to both the sparse and dense parts; a 'count' scalar is
        kept in the opt state).
      dense_optimizer: optional optax optimizer for the dense part
        (default: the optax twin of `optimizer`).
      strategy: sparse aggregation strategy ('auto' | 'sort' | 'dense' |
        'tiled' — the Pallas one-hot-matmul kernels).
      fold_sort: sort folding (ISSUE 2, default on): the tapped forward
        produces each exchange group's canonical id sort ONCE
        (TapResiduals.tp_sort/row_sort) and the sparse update consumes the
        precomputed order instead of re-sorting — bit-identical numerics,
        ≤1 sort op per (bucket, hotness) exchange group in the compiled
        step (the reference CUDA backward's reuse of forward-sorted ids,
        embedding_lookup_kernels.cu:706-773). False keeps the unfolded
        (re-sorting) step, e.g. as the parity baseline in tests.

    Returns (init_fn, step_fn):
      init_fn(params) -> opt_state
      step_fn(params, opt_state, numerical, cats, labels)
        -> (params, opt_state, loss);  jit with donated params/opt_state.
    """
    emb = model.embedding
    scheduled, sopt_for, dense_optimizer = _sparse_optimizer_setup(
        optimizer, lr, strategy, dense_optimizer,
        widths=emb.plan_widths())
    sopt = sopt_for()

    def init_fn(params):
        state = {"emb": emb.init_sparse_state(params["embedding"], sopt),
                 "dense": dense_optimizer.init(_dense_part(params))}
        if scheduled:
            state["count"] = jnp.zeros((), jnp.int32)
        return state

    off_buckets = [b for b in range(len(emb.plan.tp_buckets))
                   if emb._bucket_memory_kind(b)]
    sort_spec = (optimizer, strategy) if fold_sort else None

    def step_fn(params, opt_state, numerical, cats, labels):
        cats = list(cats)
        taps = emb.make_taps(cats)
        sopt_t = sopt_for(opt_state)

        def loss_with_taps(dense, taps):
            p = _merge_dense(dense, params)
            return model.loss_fn(p, numerical, cats, labels, taps=taps,
                                 return_residuals=True)

        dense0 = _dense_part(params)
        # residual_sort_scope is trace-time state: the model's loss_fn
        # reaches emb.apply without a residual_sort channel of its own, so
        # the fold spec rides the layer for exactly this traced region
        with emb.residual_sort_scope(sort_spec):
            (loss, res), (g_dense, g_taps) = jax.value_and_grad(
                loss_with_taps, argnums=(0, 1), has_aux=True)(dense0, taps)
        # the shared drain-stage tail (also the lookahead engine's): sparse
        # update + off-bucket output zeroing (host leaves never leave jit)
        new_emb, new_emb_state, pending = drain_sparse_apply(
            emb, params["embedding"], opt_state["emb"], g_taps, res, sopt_t,
            off_buckets)
        updates, new_dense_state = dense_optimizer.update(
            g_dense, opt_state["dense"], dense0)
        new_dense = apply_updates(dense0, updates)
        new_params = _merge_dense(new_dense, {**params, "embedding": new_emb})
        new_state = {"emb": new_emb_state, "dense": new_dense_state}
        if scheduled:
            new_state["count"] = opt_state["count"] + 1
            # concrete per-step lr for the out-of-jit host apply (offload)
            pending = {b: v + (lr(opt_state["count"]),)
                       for b, v in pending.items()}
        return new_params, new_state, loss, pending

    # jit is load-bearing, not just speed: memory-kind placement (offloaded
    # pinned-host buckets) only propagates from concrete input shardings at
    # a top-level jit boundary; donation lets XLA update tables in place.
    if donate is None:
        donate = default_donate()
    if not off_buckets:
        core = jax.jit(step_fn, donate_argnums=(0, 1) if donate else ())

        def run(params, opt_state, numerical, cats, labels):
            p, s, loss, _ = core(params, opt_state, numerical, cats, labels)
            return p, s, loss
        return init_fn, run

    # Offloaded buckets: host tables/state are READ-ONLY inside the jitted
    # step (forward lookups + dedup happen there); the host-memory row apply
    # runs afterwards at top level, where XLA honors pinned_host output
    # placement. Donation skips params/opt_state because the host leaves
    # must survive the call.
    core = jax.jit(step_fn)

    def run(params, opt_state, numerical, cats, labels):
        new_params, new_state, loss, pending = core(
            params, opt_state, numerical, cats, labels)
        tp = list(new_params["embedding"]["tp"])
        tp_s = list(new_state["emb"]["tp"])
        scales = new_params["embedding"].get("tp_scale")
        tp_scale = list(scales) if scales is not None else None
        for b, pend in pending.items():
            rep, sums, valid = pend[0], pend[1], pend[2]
            lr_t = pend[3] if len(pend) > 3 else None
            scale_b = (tp_scale[b] if tp_scale is not None else None)
            out = emb.host_bucket_apply(
                b, params["embedding"]["tp"][b], opt_state["emb"]["tp"][b],
                rep, sums, valid, sopt, lr_value=lr_t, scale_h=scale_b)
            if scale_b is not None:
                # quantized storage (ISSUE 15): the SR write-back
                # refreshed both the payload and the per-row scales
                tp[b], tp_scale[b], tp_s[b] = out
            else:
                tp[b], tp_s[b] = out
        new_emb = {**new_params["embedding"], "tp": tp}
        if tp_scale is not None:
            new_emb["tp_scale"] = tp_scale
        new_params = {**new_params, "embedding": new_emb}
        new_state = {**new_state, "emb": {**new_state["emb"], "tp": tp_s}}
        return new_params, new_state, loss

    return init_fn, run


def fit(model, params, data, steps: int, optimizer: str = "adagrad",
        lr=0.01, sparse: bool = True, opt_state=None, dense_optimizer=None,
        callbacks=(), eval_data=None, eval_every: int = 0,
        eval_steps: int = 16, log_every: int = 100, log_fn=print,
        stage=None, sync_every=None, preprocess=None, pipelined: bool = True,
        pipeline_depth=None, hot_sync_every: int = 0,
        store=None, publish_every=None, publish_dir=None,
        vocab=None, vocab_every: int = 16,
        lookahead=None, stale_ok: bool = False, registry=None):
    """Minimal training-loop driver — the role the reference fills with
    Keras `model.fit` + `DistributedOptimizer` + callbacks
    (reference dist_model_parallel.py:1270-1326, synthetic main.py:104-114).

    The loop never blocks on the loss between sync points: step dispatch is
    async, so the host stays ahead of the device the way the reference's
    graph-mode fit does (loss printed per interval, not materialized per
    step — reference examples/dlrm/main.py:219-221).

    Args:
      model: exposes `.embedding`, `loss_fn(params, numerical, cats, labels,
        taps=..., return_residuals=...)` and (for eval) `apply`.
      params: initial parameter pytree ({'embedding': ..., ...}).
      data: iterable/callable yielding (numerical, cats, labels) batches
        (jax or numpy arrays; a callable receives the step index).
      steps: number of optimizer steps.
      optimizer / lr / dense_optimizer: see make_sparse_train_step.
      sparse: use the sparse tapped path (default) or dense optax grads.
      callbacks: objects with optional `on_train_begin(params)` (e.g.
        BroadcastGlobalVariablesCallback) and/or
        `on_step(step, params, loss)` hooks (loss is a device scalar —
        call float() in the callback only if you accept a sync).
      eval_data / eval_every / eval_steps: run `evaluate` periodically.
      stage: per-batch staging function applied in the ingestion pipeline
        for iterable `data` (e.g. ``lambda b: stage_dp_batch(mesh, b)``).
        Default: mesh-aware dp staging when the model has a mesh, plain
        device_put otherwise. Multi-process numpy iterables require the
        mesh-aware form — a committed single-device array cannot be
        resharded onto a non-addressable global mesh.
      preprocess: optional host transform run between the reader and the
        staging worker (e.g. ``RawBinaryDataset.preprocess`` when `data`
        yields raw buffers, or an IntegerLookup raw-key translation).
        Iterable `data` only.
      pipelined: True (default) runs read/preprocess/stage each in a
        persistent background worker (utils.pipeline.IngestPipeline) so
        host ingestion overlaps the device step; False keeps the serial
        inline form (identical batch order — the A/B baseline). Iterable
        `data` only; callable `data` is always pulled inline.
      pipeline_depth: bound of each inter-stage queue (backpressure).
        ``None`` (default) resolves ``DET_PIPELINE_DEPTH`` through the
        tune seam (env > tuned config > measured defaults > 2).
      sync_every: block on the loss every N steps. Default: 1 on
        multi-process runs (keeps per-process collectives in lockstep)
        and on the CPU backend (XLA:CPU's in-process collectives can
        deadlock when many steps are dispatched asynchronously), else 0
        (TPU: never block mid-run).
      store / publish_every / publish_dir: weight streaming (ISSUE 6):
        pass a `store.TableStore` over `params["embedding"]` and a
        publish cadence to turn this run into a live publisher — every
        step's touched-row keys accumulate host-side
        (`store.observe`; per-step numpy work proportional to the
        batch's unique ids — the price of delta completeness, unlike
        the SAMPLED hot-admission feed below), and every
        `publish_every` steps (``None`` resolves ``DET_PUBLISH_EVERY``
        through the tune seam, default 0 = disabled) the loop commits
        the current pytrees and
        writes the next row-delta file (first publish = full snapshot)
        into `publish_dir` for `InferenceEngine.poll_updates` replicas.
        Leftover steps publish once more at the end. Sparse path only.
        History gains a 'published' list of publish infos.
      vocab / vocab_every: dynamic vocabulary (ISSUE 7, sparse path
        only): pass a `vocab.VocabManager` over `model.embedding` and
        the loop treats every batch's categorical inputs as RAW keys —
        each step translates them to physical rows host-side (unknown
        keys ride the fallback row) and feeds the admission tracker;
        every `vocab_every` steps the manager runs one
        admission/eviction cycle against the live params/opt-state
        (`maintain` — shapes never change, so the jitted step never
        recompiles). `vocab_every=0` disables maintenance entirely
        (translate/observe only — the 0-disables idiom of
        publish_every/hot_sync_every). Composes with publishing:
        rebound rows merge into the next delta's key set and the
        binding state is published as a ``vocab_v{version}.npz``
        sidecar consumers (`InferenceEngine.poll_updates`) load
        alongside the rows. History gains 'vocab_stats'.
      lookahead / stale_ok: device-pipeline depth (ISSUE 9, sparse path
        only). ``lookahead=1`` runs training through a
        `schedule.LookaheadEngine`: batch N+1's id exchange, table
        gather and activation all_to_all are issued in the same fused
        device program as batch N's dense forward/backward (no data
        dependency between them — auditable, see tools/hlo_audit.py's
        overlap arm), with the gradient transpose + sparse update
        trailing as the drain stage. Bit-exact against lookahead=0 by
        default (the engine patches prefetched activations for rows the
        previous step touched); ``stale_ok=True`` skips the patch with
        documented one-step-stale semantics (docs/userguide.md).
        ``lookahead=None`` reads ``DET_LOOKAHEAD`` (default 0).
        Refused compositions (loudly, here at fit time): the dense
        (sparse=False) path, hot-row replication (`hot_sync_every` /
        hot-sharded layers), and a `VocabManager` with maintenance
        cycles (``vocab_every != 0``) — a mid-window evict+rebind would
        invalidate already-prefetched physical rows. Translate-only
        vocab use (``vocab_every=0``) composes: batches are translated
        when PULLED, before the engine prefetches them.
      registry: optional `obs.MetricRegistry` — the run's ONE metric
        namespace (ISSUE 11). fit threads it through everything it
        drives: the ingest pipeline (``ingest/stage_seconds{stage=}``),
        the lookahead engine (patch counters + the compile-count
        gauges), and — via their ``use_registry`` rebind, only when an
        explicit registry is passed here — the publisher `store` and
        the `vocab` manager (a caller-attached registry on those
        components is respected otherwise); fit's own loop adds
        ``span_seconds{span=train/step}`` wall-time spans,
        ``train/steps`` / ``train/examples`` counters, the
        ``train/examples_per_sec`` / ``train/publish_cadence_steps``
        gauges, and the static ``exchange/*`` gauges from
        `exchange_padding_report` (exported at run end, so they reflect
        the final vocab occupancy). ``None`` creates a private per-run
        registry — either way the final snapshot lands in
        ``history["metrics_snapshot"]``, and ``DET_OBS_EXPORT=<path>``
        appends it as one JSONL line there (the soak-run export).
      hot_sync_every: hot-row replication cadence (layers built with
        `hot_rows=`, sparse path only): every N steps the loop runs
        `sync_hot_rows(admit=True)` — write hot rows back to the
        canonical tables and re-admit the currently-hottest set. The
        frequency feed (`observe_hot_ids` — host-side numpy counter
        work) is SAMPLED, not per-step: ~8 observed batches per sync
        window (`max(1, N // 8)` stride), because the per-unique-key
        counter update is real host time and zipfian admission only
        needs a frequency ESTIMATE — per-step observation would
        serialize exactly the class of host work the ingest pipeline
        exists to hide. 0 (default) leaves admission entirely to the
        caller.

    Returns (params, opt_state, history) — history is a dict of lists
    ('loss' as floats, drained from device at sync/log boundaries;
    optionally 'eval_auc').
    """
    from distributed_embeddings_tpu.obs.registry import MetricRegistry
    from distributed_embeddings_tpu.obs.spans import span
    from distributed_embeddings_tpu.tune import resolve as _tune_resolve
    reg = registry if registry is not None else MetricRegistry()
    if pipeline_depth is None:
        pipeline_depth = int(_tune_resolve.knob_value(
            "DET_PIPELINE_DEPTH", "2"))
    if publish_every is None:
        publish_every = int(_tune_resolve.knob_value(
            "DET_PUBLISH_EVERY", "0"))
    if lookahead is None:
        from distributed_embeddings_tpu.schedule import default_lookahead
        lookahead = default_lookahead()
    la_engine = None
    if lookahead:
        # unsupported compositions are refused HERE, loudly, not degraded:
        if not sparse:
            raise ValueError(
                "lookahead requires the sparse tapped path (sparse=True)")
        if hot_sync_every or getattr(getattr(model, "embedding", None),
                                     "_hot_buckets", None):
            raise NotImplementedError(
                "lookahead>0 does not compose with hot-row replication: "
                "the replicated hot shard moves densely every step, so "
                "prefetched activations cannot be patched from the "
                "touched-row set (at most one of hot_rows / lookahead "
                "per run, mirroring the hot-rows x vocab refusal)")
        if vocab is not None and vocab_every:
            raise NotImplementedError(
                "lookahead>0 does not compose with VocabManager "
                "maintenance cycles (vocab_every != 0): a same-window "
                "evict+rebind would invalidate physical rows the engine "
                "already prefetched — run with vocab_every=0 "
                "(translate-only) or lookahead=0")
        from distributed_embeddings_tpu.schedule import LookaheadEngine
        la_engine = LookaheadEngine(
            model, optimizer, lr=lr, dense_optimizer=dense_optimizer,
            lookahead=lookahead, stale_ok=stale_ok, registry=reg)
        step_fn = None
        if opt_state is None:
            opt_state = la_engine.init(params)
    elif sparse:
        init_fn, step_fn = make_sparse_train_step(
            model, optimizer, lr=lr, dense_optimizer=dense_optimizer)
        if opt_state is None:
            opt_state = init_fn(params)
    else:
        import optax
        opt = dense_optimizer or {
            "sgd": lambda: optax.sgd(lr),
            "adagrad": lambda: optax.adagrad(lr),
            "adam": lambda: optax.adam(lr)}[optimizer]()

        def loss_fn(p, numerical, cats, labels):
            return model.loss_fn(p, numerical, cats, labels)
        step_fn = make_train_step(loss_fn, opt, donate=False)
        if opt_state is None:
            opt_state = opt.init(params)

    for cb in callbacks:
        if hasattr(cb, "on_train_begin"):
            params = cb.on_train_begin(params)

    if sync_every is None:
        sync_every = (1 if (jax.process_count() > 1
                            or jax.default_backend() == "cpu") else 0)

    get_batch = data if callable(data) else None
    pipeline = None
    if get_batch is None:
        # full ingestion overlap: read, preprocess and device staging each
        # run in a persistent worker thread ahead of the consumer, so the
        # host-side batch cost hides under the device step (the reference's
        # prefetch-executor role, examples/dlrm/utils.py:231-254, extended
        # to every stage — docs/perf_model.md "Ingestion pipeline")
        from distributed_embeddings_tpu.utils.pipeline import staged_batches
        if stage is None:
            mesh = getattr(getattr(model, "embedding", None), "mesh", None)
            if mesh is not None:
                from distributed_embeddings_tpu.parallel.staging import (
                    stage_dp_batch)
                stage = lambda b: stage_dp_batch(mesh, b)  # noqa: E731
        # islice: the background reader must never pull past the batches
        # this run will consume — an over-pull would silently eat items
        # from a shared/reused source iterator when close() drains
        import itertools
        pipeline = staged_batches(itertools.islice(iter(data), steps),
                                  stage=stage, preprocess=preprocess,
                                  depth=pipeline_depth, pipelined=pipelined,
                                  registry=reg)
        it = iter(pipeline)
    else:
        it = None
    history = {"loss": []}
    pending = []     # device scalars since the last sync; drained to floats
    # at sync/log boundaries (where a block happens anyway) so long runs
    # never hold an unbounded number of live device buffers

    def drain():
        history["loss"].extend(float(l) for l in jax.device_get(pending))
        pending.clear()

    hot_emb = getattr(model, "embedding", None)
    hot_active = (sparse and hot_sync_every
                  and getattr(hot_emb, "_hot_buckets", None))
    hot_observe_stride = max(1, hot_sync_every // 8) if hot_active else 0
    publishing = bool(sparse and store is not None and publish_every)
    if publishing and publish_dir is None:
        raise ValueError("publish_every requires publish_dir")
    # one metric namespace per run (ISSUE 11): with an EXPLICIT run
    # registry, caller-built components rebind onto it so their
    # counters land in the same snapshot as fit's own. Without one,
    # they keep whatever registry they were built with — silently
    # stealing a store/vocab off a registry the caller attached for
    # their own export would freeze that registry mid-run.
    if registry is not None:
        if store is not None:
            store.use_registry(reg)
        if vocab is not None:
            vocab.use_registry(reg)
    if publishing:
        reg.gauge("train/publish_cadence_steps").set(publish_every)
    if vocab is not None and not sparse:
        raise ValueError("vocab management requires the sparse path "
                         "(sparse=True)")
    if vocab is not None and vocab.emb is not getattr(model, "embedding",
                                                      None):
        # same guard InferenceEngine applies: the manager's flat row
        # keys are plan-specific — maintaining another layer's params
        # with them would scatter into wrong rows silently
        raise ValueError(
            "vocab manager was built over a different layer than "
            "model.embedding; binding rows are plan-specific")
    steps_since_publish = 0

    def publish_now():
        drain()                     # params are about to be read host-side
        store.commit(params["embedding"], opt_state["emb"],
                     touched=(vocab.drain_touched()
                              if vocab is not None else None))
        from distributed_embeddings_tpu import faults
        try:
            if vocab is not None:
                # binding sidecar for the version about to publish —
                # written BEFORE the stream file, so any consumer that
                # can see the rows can also see the matching key->row
                # map (the reverse order would open a window where a
                # poll applies version V's rows but only finds the V-1
                # binding)
                from distributed_embeddings_tpu.vocab import (
                    vocab_state_path)
                import os as _os
                _os.makedirs(publish_dir, exist_ok=True)
                # full=False: the publish sidecar is the serving-grade
                # binding (keys + free list), NOT the trainer's counters
                # and stash — those are checkpoint state and would make
                # every sidecar table-sized under sustained drift
                vocab.save_state(
                    vocab_state_path(publish_dir, store.version),
                    full=False)
            history.setdefault("published", []).append(
                store.publish(publish_dir))
        except faults.InjectedCrash as e:
            # simulated publisher crash+restart (ISSUE 13): the tmp file
            # is orphaned on disk (the restarted publisher's first
            # publish sweeps it), nothing was renamed into the stream,
            # and the store's pending touched keys survive — the next
            # cadence republishes them under a later version, so no
            # consumer ever misses a row. ONLY the injected type is
            # caught; real publish failures still propagate.
            reg.counter("store/publish_crashes_total").inc()
            history.setdefault("publish_crashes", []).append(str(e)[:200])

    def pull(s):
        b = get_batch(s) if get_batch else next(it)
        if la_engine is not None and vocab is not None:
            # translate at PULL time under lookahead: the engine
            # prefetches this batch's exchange before the loop body
            # consumes it, so raw->physical translation must happen
            # first. Maintenance is refused with lookahead, so the
            # binding the early translation sees is the same one the
            # consume step would.
            n, c, lbl = b
            b = (n, vocab.translate(list(c), observe=True), lbl)
        return b

    next_batch = None
    examples_total = 0
    # per-strategy update-phase attribution (ISSUE 12): the step span
    # gains a nested span whose PATH names the sparse-update kernel
    # family the traced step dispatches to (xla/tiled/pallas — resolved
    # once, from the env knobs + cached gate verdicts), so snapshots and
    # the soak harness can see WHICH path actually ran. Like train/step
    # itself this times the host-side dispatch; the count/label is the
    # signal, not the duration.
    if sparse:
        from distributed_embeddings_tpu.ops.sparse_update import (
            active_scatter_impl)
        update_impl = active_scatter_impl()
    else:
        update_impl = "dense"
    import time as _time
    t_run0 = _time.perf_counter()
    try:
        for step in range(steps):
            if la_engine is not None:
                batch = next_batch if next_batch is not None else pull(step)
                next_batch = pull(step + 1) if step + 1 < steps else None
            else:
                batch = pull(step)
            numerical, cats, labels = batch
            if vocab is not None and la_engine is None:
                # maintain BEFORE translating this batch: a maintain
                # cycle can evict key K and immediately rebind K's freed
                # row to a fresh key — a batch translated before the
                # cycle would still carry K -> row and land K's gradient
                # on the new tenant's zero-initialized row. Maintaining
                # first means every translation this step sees the
                # post-cycle binding.
                if vocab_every and step and step % vocab_every == 0:
                    p_emb, s_emb = vocab.maintain(params["embedding"],
                                                  opt_state["emb"])
                    params = {**params, "embedding": p_emb}
                    opt_state = {**opt_state, "emb": s_emb}
                # raw keys -> physical rows (host-side; admission
                # counters fed from the same stream), BEFORE the store's
                # touched-row observation — the delta key space is
                # physical rows
                cats = vocab.translate(list(cats), observe=True)
            if publishing:
                # EVERY step: the delta's key set must cover every row
                # the update touches (a sampled feed would silently
                # drop rows from the published view)
                store.observe(list(cats))
            if hot_active:
                if step % hot_observe_stride == 0:
                    hot_emb.observe_hot_ids(list(cats))
                if step and step % hot_sync_every == 0:
                    drain()     # params are about to be rewritten: sync
                    p_emb, s_emb = hot_emb.sync_hot_rows(
                        params["embedding"], opt_state["emb"], admit=True)
                    params = {**params, "embedding": p_emb}
                    opt_state = {**opt_state, "emb": s_emb}
            # span = host wall time of the step DISPATCH (plus any host
            # work the engine does); device time hides behind async
            # dispatch except at sync boundaries — the honest host-side
            # reading, same clock the reference's fit loop shows
            with span("train/step", reg), \
                    span(f"update/{update_impl}", reg):
                if la_engine is not None:
                    params, opt_state, loss = la_engine.step(
                        params, opt_state, batch, next_batch)
                else:
                    params, opt_state, loss = step_fn(
                        params, opt_state, jnp.asarray(numerical),
                        [jnp.asarray(c) for c in cats],
                        jnp.asarray(labels))
            pending.append(loss)
            shp = getattr(labels, "shape", None)
            n_ex = int(shp[0]) if shp else len(labels)
            examples_total += n_ex
            reg.counter("train/steps").inc()
            reg.counter("train/examples").inc(n_ex)
            if publishing:
                steps_since_publish += 1
                if steps_since_publish >= publish_every:
                    publish_now()
                    steps_since_publish = 0
            if sync_every and (step + 1) % sync_every == 0:
                drain()                       # explicit lockstep barrier
            if log_every and step % log_every == 0:
                drain()
                log_fn(f"step {step}/{steps}: loss={history['loss'][-1]:.5f}")
            elif len(pending) >= 4096:
                drain()    # no-sync runs still bound live device buffers
            for cb in callbacks:
                if hasattr(cb, "on_step"):
                    cb.on_step(step, params, loss)
            if eval_data is not None and eval_every and \
                    (step + 1) % eval_every == 0:
                auc = evaluate(model, params, eval_data, eval_steps)
                history.setdefault("eval_auc", []).append(auc)
                log_fn(f"step {step}: eval AUC={auc:.5f}")
    finally:
        if pipeline is not None:
            # ingestion accounting rides the history so callers (and the
            # bench record) can see where host time went this run
            history["ingest_stages"] = pipeline.stage_summaries()
            pipeline.close()
    drain()
    if la_engine is not None:
        history["lookahead_stats"] = dict(la_engine.stats)
    if hot_active:
        # leave the returned params canonical-consistent (hot rows written
        # back; residency unchanged) so raw-param consumers need no extra
        # sync — a numeric no-op for the training state itself
        p_emb, s_emb = hot_emb.sync_hot_rows(params["embedding"],
                                             opt_state["emb"])
        params = {**params, "embedding": p_emb}
        opt_state = {**opt_state, "emb": s_emb}
        history["hot_stats"] = hot_emb.hot_stats()
    if vocab is not None:
        if vocab_every:
            # tail cycle: keys that crossed the threshold after the last
            # scheduled maintain still admit before the run hands back
            # (vocab_every=0 = maintenance off: translate/observe only,
            # matching publish_every/hot_sync_every's 0-disables idiom)
            p_emb, s_emb = vocab.maintain(params["embedding"],
                                          opt_state["emb"])
            params = {**params, "embedding": p_emb}
            opt_state = {**opt_state, "emb": s_emb}
        history["vocab_stats"] = vocab.stats()
    if publishing and (steps_since_publish
                       or (vocab is not None and vocab.pending_publication)):
        # leftover tail steps — and any rows the tail vocab cycle just
        # rebound — reach replicas too
        publish_now()
    # ---- run-end telemetry (ISSUE 11): throughput gauge, the static
    # exchange/* gauges (exported LAST so occupancy reflects the tail
    # vocab cycle), the embedded snapshot, and the JSONL export hook
    elapsed = max(_time.perf_counter() - t_run0, 1e-9)
    reg.gauge("train/examples_per_sec").set(examples_total / elapsed)
    try:
        # kernel dispatch telemetry (ISSUE 12): gate verdicts per impl so
        # the SLO rule file can require the verdict's presence
        from distributed_embeddings_tpu.obs.instrument import (
            export_kernel_gauges)
        export_kernel_gauges(reg)
    except Exception as e:  # noqa: BLE001 - accounting never kills a run
        history["metrics_error"] = str(e)[:200]
    emb = getattr(model, "embedding", None)
    if emb is not None and hasattr(emb, "exchange_padding_report"):
        try:
            from distributed_embeddings_tpu.obs.instrument import (
                export_exchange_gauges)
            export_exchange_gauges(
                reg, emb, batch=max(examples_total // max(steps, 1), 1),
                vocab=vocab, lookahead=int(lookahead or 0))
        except Exception as e:  # noqa: BLE001 - accounting never kills a run
            history["metrics_error"] = str(e)[:200]
    history["metrics_snapshot"] = reg.snapshot()
    export_path = os.environ.get("DET_OBS_EXPORT")
    if export_path:
        # fsync: this is the run's FINAL export line — the postmortem
        # tail a crashed follow-on must still find on disk
        reg.export_jsonl(export_path, extra={"source": "fit"}, fsync=True)
    trace_path = os.environ.get("DET_OBS_TRACE")
    if trace_path:
        # flight-recorder window as a Perfetto-loadable chrome trace
        # (ISSUE 14): span timeline + version-lineage tracks for this run
        try:
            from distributed_embeddings_tpu.obs.trace import (
                default_recorder)
            default_recorder().export(trace_path)
        except Exception as e:  # noqa: BLE001 - accounting never kills a run
            history["metrics_error"] = str(e)[:200]
    return params, opt_state, history


def evaluate(model, params, data, steps: int = 16, preprocess=None,
             pipelined: bool = True) -> float:
    """Streaming AUC over `steps` batches (the reference's eval loop,
    examples/dlrm/main.py:223-243, without the hvd.allgather — outputs are
    already global jax.Arrays under SPMD). Iterable `data` is pulled through
    the background ingestion pipeline (read/preprocess workers) like `fit`;
    staging stays in the consumer here because the forward's inputs are
    tiny and eval runs are short."""
    from distributed_embeddings_tpu.utils.metrics import StreamingAUC

    auc = StreamingAUC()
    state = auc.init()
    get_batch = data if callable(data) else None
    pipeline = None
    if get_batch is None:
        import itertools
        from distributed_embeddings_tpu.utils.pipeline import (
            IngestPipeline, SerialPipeline)
        stages = ([("preprocess", preprocess)] if preprocess is not None
                  else [])
        # islice bounds the background read-ahead to exactly `steps`
        # items: eval is often called repeatedly on one shared iterator
        # (fit's eval_every loop) and must not eat batches beyond its run
        source = itertools.islice(iter(data), steps)
        pipeline = (IngestPipeline(source, stages) if pipelined
                    else SerialPipeline(source, stages))
        it = iter(pipeline)
    else:
        it = None
    fwd = jax.jit(lambda p, n, c: model.apply(p, n, c))
    try:
        for step in range(steps):
            numerical, cats, labels = (get_batch(step) if get_batch
                                       else next(it))
            logits = fwd(params, jnp.asarray(numerical),
                         [jnp.asarray(c) for c in cats])
            state = auc.update(state, jnp.asarray(labels).reshape(-1),
                               logits.reshape(-1))
    finally:
        if pipeline is not None:
            pipeline.close()
    return float(auc.result(state))
