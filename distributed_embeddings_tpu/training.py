"""Training integration: the reference's Horovod-patch layer, re-thought SPMD.

The reference ships four Horovod integration shims
(reference: distributed_embeddings/python/layers/dist_model_parallel.py:1217-1326):

  * ``DistributedGradientTape`` (:1242) — patches Horovod's tape so
    model-parallel variables (tagged ``var.de_local``) are excluded from the
    allreduce while data-parallel grads are averaged.
  * ``DistributedOptimizer`` (:1270) — same patch for the Keras-fit path.
  * ``broadcast_variables`` (:1219) — initial DP weight sync that skips MP vars.
  * ``BroadcastGlobalVariablesCallback`` (:1303) — Keras callback form.

Under SPMD none of the patching is load-bearing: a jit-compiled train step
over a Mesh computes gradients that automatically follow parameter shardings
(MP-sharded grads stay device-local; replicated-param grads are psummed by the
shard_map/pjit transpose), and every process builds identical initial weights
from the same seed. The behavioral contract — "MP gradients never cross
workers, DP gradients are averaged, one backward pass handles both" (:1242-1267)
— is a property of sharded autodiff here, not of a wrapper.

These classes therefore exist for API parity and for the places where a real
action remains (multi-process weight sync from process-local state, gradient
postprocessing hooks). They are thin, documented, and jit-compatible.
"""

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from distributed_embeddings_tpu.layers.dist_model_parallel import (
    broadcast_variables)

__all__ = [
    "DistributedGradientTape",
    "DistributedOptimizer",
    "BroadcastGlobalVariablesCallback",
    "broadcast_variables",
    "make_train_step",
]


class DistributedGradientTape:
    """API-parity shim for the reference DistributedGradientTape (:1242).

    Usage: ``tape = DistributedGradientTape(); loss, grads =
    tape.gradient(loss_fn, params, *args)``. The heavy lifting the reference
    wrapper did (allreduce DP grads, keep MP grads local, sparse_as_dense) is
    inherent to sharded autodiff — grads follow param shardings.
    """

    def __init__(self, sparse_as_dense: bool = True):
        # sparse_as_dense is vacuous: XLA grads of gather are dense
        # scatter-adds already (no IndexedSlices analogue in JAX).
        del sparse_as_dense

    def gradient(self, loss_fn: Callable, params, *args, **kwargs):
        return jax.value_and_grad(loss_fn)(params, *args, **kwargs)


class DistributedOptimizer:
    """Optax wrapper with the reference DistributedOptimizer API (:1270).

    ``init``/``update`` pass through to the wrapped optax optimizer; no
    gradient communication is inserted because none is needed (see module
    docstring). Keeps a hook point (``postprocess``) mirroring the
    reference's gradient-postprocess ability.
    """

    def __init__(self, optimizer,
                 postprocess: Optional[Callable[[Any], Any]] = None):
        self._opt = optimizer
        self._postprocess = postprocess

    def init(self, params):
        return self._opt.init(params)

    def update(self, grads, opt_state, params=None):
        if self._postprocess is not None:
            grads = self._postprocess(grads)
        return self._opt.update(grads, opt_state, params)

    def apply(self, params, updates):
        return apply_updates(params, updates)


class BroadcastGlobalVariablesCallback:
    """API-parity shim for the reference Keras callback (:1303).

    Under SPMD the initial weights are already identical (same program, same
    seed). For multi-process runs restoring from process-local state, call
    ``on_train_begin(params)`` to broadcast from process 0.
    """

    def __init__(self, root_rank: int = 0):
        if root_rank != 0:
            raise NotImplementedError(
                "broadcast_one_to_all always originates from process 0; "
                "root_rank != 0 is not supported")
        self.root_rank = root_rank
        self._done = False

    def on_train_begin(self, params):
        if self._done:
            return params
        self._done = True
        return broadcast_variables(params, root_rank=self.root_rank)


def apply_updates(params, updates):
    """params + updates (optax convention). Delegates to optax.apply_updates,
    which also handles None update leaves (masked optimizers) and casts
    updates to each param's dtype."""
    import optax
    return optax.apply_updates(params, updates)


def make_train_step(loss_fn: Callable, optimizer, donate: bool = True,
                    param_shardings: Any = None):
    """Build the canonical jitted SPMD train step.

    Args:
      loss_fn: (params, *batch) -> scalar loss (mean over the global batch —
        this is what makes replicated-param grads come out averaged, the
        reference's hvd.allreduce(average) semantics :1260).
      optimizer: optax optimizer (or DistributedOptimizer).
      donate: donate params/opt_state buffers (in-place update on TPU).
      param_shardings: optional full params-tree sharding pytree, pinned on
        the step's params output (keeps placement stable across steps).

    Returns:
      step(params, opt_state, *batch) -> (params, opt_state, loss), jitted.
    """
    def step(params, opt_state, *batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, *batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = apply_updates(params, updates)
        return params, opt_state, loss

    donate_argnums = (0, 1) if donate else ()
    out_shardings = ((param_shardings, None, None)
                     if param_shardings is not None else None)
    return jax.jit(step, donate_argnums=donate_argnums,
                   out_shardings=out_shardings)
