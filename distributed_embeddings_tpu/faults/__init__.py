"""Deterministic fault-injection seam (ISSUE 13) — see `faults.plan`.

Inert unless a `FaultPlan` is installed (``DET_FAULT_PLAN`` env or
`set_plan`/`use_plan`); the IO seams in store/, vocab/, serving/ and
utils/pipeline.py call `check`/`check_raise`/`filter_scan` and degrade
per docs/serving.md "Failure modes & degradation".
"""

from distributed_embeddings_tpu.faults.plan import (  # noqa: F401
    CORRUPTING_KINDS, KINDS, POINTS, FaultError, FaultPlan, FaultSpec,
    InjectedCrash, InjectedIOError, active_plan, check, check_raise,
    corrupt_file, filter_scan, reset_plan, set_plan, use_plan)

__all__ = [
    "CORRUPTING_KINDS", "KINDS", "POINTS",
    "FaultError", "FaultPlan", "FaultSpec",
    "InjectedCrash", "InjectedIOError",
    "active_plan", "check", "check_raise", "corrupt_file", "filter_scan",
    "reset_plan", "set_plan", "use_plan",
]
