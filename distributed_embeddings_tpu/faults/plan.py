"""Deterministic fault injection for the streaming/serving spine (ISSUE 13).

The train->serve path (store/ publishers, vocab sidecars, DeltaConsumer
replicas, the ingest pipeline) assumed a benign filesystem and a
crash-free publisher. This module is the adversary: a seed-driven
`FaultPlan` whose injection points wrap the EXISTING IO seams — nothing
here changes behavior unless a plan is installed, and every decision a
plan makes is a pure function of (seed, call sequence), so a soak run
that found a degradation replays bit-identically from its scenario file.

Injection points (the seam calls `faults.check(point, ...)` /
`faults.filter_scan(point, files)`):

  * ``store.publish``     — `TableStore.publish`'s write+rename. Kinds:
    ``truncate`` / ``bit_flip`` (the renamed-in file is corrupt — the
    torn/partial-write classes), ``crash_before_rename`` (the tmp file
    is orphaned, the stream file never appears; raises `InjectedCrash`,
    which `training.fit`'s publisher catches and survives), ``pause``
    (the publish is skipped entirely — publisher pause/resume).
  * ``vocab.save_state``  — the vocab sidecar writer; same write kinds.
  * ``store.scan``        — `scan_published`. Kind ``delay_visibility``:
    a newly published file stays invisible to consumers for N scans
    (NFS/FUSE-style lagging directory views).
  * ``store.load``        — `load_row_delta`/`load_row_delta_meta`.
    Kind ``io_error``: raise `InjectedIOError` (an `OSError`) —
    the transient-read class the consumer retries with backoff.
  * ``consumer.poll``     — `DeltaConsumer.poll` entry; ``io_error``.
  * ``ingest.stage``      — ingest-pipeline stage bodies; ``io_error``
    (the stage worker retries transient errors in place).
  * ``fleet.canary_apply`` — the fleet rollout's canary-evaluation seam
    (ISSUE 16). Kind ``bit_flip``: the canary replica's freshly-applied
    table state is perturbed IN MEMORY (one element) before the parity
    check — the apply-went-wrong class the canaried rollout must catch
    and roll back; the stream files on disk stay healthy.

A plan is data:  ``{"seed": 7, "faults": [{"point": "store.publish",
"kind": "bit_flip", "at": [1]}, ...]}`` — installed via the
``DET_FAULT_PLAN`` env var (inline JSON or a path to a JSON file) or
the `set_plan`/`use_plan` API. Each spec fires on explicit 0-based
occurrence indices (``at`` + optional ``repeat``) or on a seeded
per-occurrence Bernoulli draw (``prob``), capped by ``max_fires``.
Every firing lands in ``plan.events`` — the ledger the soak harness
reconciles quarantine/retry/orphan counts against.
"""

import json
import os
import threading
from contextlib import contextmanager
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "KINDS", "POINTS", "FaultError", "InjectedCrash", "InjectedIOError",
    "FaultSpec", "FaultPlan", "active_plan", "set_plan", "reset_plan",
    "use_plan", "check", "check_raise", "filter_scan", "corrupt_file",
]

KINDS = ("truncate", "bit_flip", "crash_before_rename", "pause",
         "delay_visibility", "io_error")

# which kinds are meaningful at which seam — a spec outside this table is
# a scenario bug and refuses at construction (a fault that can never fire
# would silently void the reconciliation ledger)
POINTS: Dict[str, Tuple[str, ...]] = {
    "store.publish": ("truncate", "bit_flip", "crash_before_rename",
                      "pause"),
    "vocab.save_state": ("truncate", "bit_flip", "crash_before_rename"),
    "store.scan": ("delay_visibility",),
    "store.load": ("io_error",),
    "consumer.poll": ("io_error",),
    "ingest.stage": ("io_error",),
    "fleet.canary_apply": ("bit_flip",),
}

# kinds that leave a CORRUPT published file behind (the quarantine set a
# soak reconciles against); crash/pause leave no stream file at all
CORRUPTING_KINDS = ("truncate", "bit_flip")


class FaultError(RuntimeError):
    """Base of all injected failures."""


class InjectedCrash(FaultError):
    """Simulated publisher crash between write and rename. The tmp file
    is left orphaned on disk; callers that model a restartable publisher
    (`training.fit`, the soak harness) catch THIS type only — real
    exceptions still propagate."""


class InjectedIOError(OSError, FaultError):
    """Simulated transient read error — an `OSError`, so it takes the
    same retry/backoff path real filesystem flakes do."""


class FaultSpec:
    """One fault rule: where (`point`), what (`kind`), when (`at`
    occurrence indices + `repeat` width, or Bernoulli `prob`), how often
    at most (`max_fires`), and a kind-specific `arg` (truncate fraction,
    bit-flip offset fraction, delay-visibility scan count)."""

    __slots__ = ("point", "kind", "at", "repeat", "prob", "max_fires",
                 "arg", "fires", "_rng", "_delay")

    _ARG_DEFAULT = {"truncate": 0.5, "bit_flip": 0.6,
                    "delay_visibility": 3}

    def __init__(self, point: str, kind: str,
                 at: Optional[Sequence[int]] = None, repeat: int = 1,
                 prob: float = 0.0, max_fires: Optional[int] = None,
                 arg: Optional[float] = None, seed: int = 0):
        if point not in POINTS:
            raise ValueError(
                f"unknown fault point {point!r} (one of {sorted(POINTS)})")
        if kind not in POINTS[point]:
            raise ValueError(
                f"fault kind {kind!r} cannot fire at point {point!r} "
                f"(supported there: {POINTS[point]})")
        if at is None and not prob:
            raise ValueError(
                f"fault ({point}, {kind}): need 'at' occurrence indices "
                "or a 'prob' > 0 — a spec with neither never fires")
        if at is not None and (not hasattr(at, "__iter__")
                               or isinstance(at, (str, bytes))):
            raise ValueError(f"fault ({point}, {kind}): 'at' must be a "
                             f"list of occurrence indices, got {at!r}")
        self.point = point
        self.kind = kind
        self.at = None if at is None else sorted(int(a) for a in at)
        self.repeat = max(int(repeat), 1)
        self.prob = float(prob)
        self.max_fires = None if max_fires is None else int(max_fires)
        self.arg = self._ARG_DEFAULT.get(kind) if arg is None else arg
        self.fires = 0
        self._rng = np.random.RandomState(seed & 0x7FFFFFFF)
        # delay_visibility state: distinct-file index assignment and
        # per-path remaining-hidden scan counts
        self._delay = {"next_idx": 0, "seen": {}, "hiding": {}}

    def budget_left(self) -> bool:
        return self.max_fires is None or self.fires < self.max_fires

    def wants(self, occurrence: int) -> bool:
        """Pure decision for one occurrence index. `at`-triggered specs
        are fully deterministic; `prob` specs draw from the spec's own
        seeded stream (deterministic per seed AND call sequence)."""
        if self.at is not None:
            return any(a <= occurrence < a + self.repeat for a in self.at)
        return bool(self._rng.random_sample() < self.prob)

    def to_dict(self) -> dict:
        return {"point": self.point, "kind": self.kind, "at": self.at,
                "repeat": self.repeat, "prob": self.prob,
                "max_fires": self.max_fires, "arg": self.arg,
                "fires": self.fires}


class FaultPlan:
    """A seed + an ordered list of `FaultSpec`s, with per-point
    occurrence counters and the event ledger. Thread-safe: publisher and
    consumer threads share one plan in a soak run."""

    def __init__(self, faults: Sequence[dict], seed: int = 0):
        self.seed = int(seed)
        self.specs: List[FaultSpec] = []
        for i, f in enumerate(faults):
            f = dict(f)
            f.pop("seed", None)
            self.specs.append(FaultSpec(seed=self.seed * 1000003 + i, **f))
        self._occ: Dict[str, int] = {}
        self.events: List[dict] = []
        self._lock = threading.Lock()

    # ------------------------------------------------------------ loading
    @classmethod
    def from_json(cls, doc) -> "FaultPlan":
        """Build from a dict, an inline JSON string, or a path to a JSON
        file (the three forms `DET_FAULT_PLAN` accepts)."""
        if isinstance(doc, str):
            text = doc.strip()
            if text.startswith("@"):
                with open(text[1:]) as f:
                    doc = json.load(f)
            elif text.startswith("{") or text.startswith("["):
                doc = json.loads(text)
            else:
                with open(text) as f:
                    doc = json.load(f)
        if isinstance(doc, list):
            doc = {"faults": doc}
        if not isinstance(doc, dict):
            raise ValueError(f"fault plan must be a dict, got {type(doc)}")
        return cls(doc.get("faults", []), seed=doc.get("seed", 0))

    def to_dict(self) -> dict:
        return {"seed": self.seed,
                "faults": [s.to_dict() for s in self.specs]}

    # ------------------------------------------------------------ firing
    def check(self, point: str, **ctx) -> Optional[FaultSpec]:
        """Advance `point`'s occurrence counter and return the first
        matching spec that fires (None = proceed normally). The firing
        is appended to `events` with the context the seam passed."""
        with self._lock:
            occ = self._occ.get(point, 0)
            self._occ[point] = occ + 1
            for spec in self.specs:
                if spec.point != point or spec.kind == "delay_visibility":
                    continue
                if not spec.budget_left():
                    continue
                if spec.wants(occ):
                    spec.fires += 1
                    # ctx keys must not clobber the ledger's identity
                    # fields — reconciliation reads event["kind"] —
                    # and "path" stays untruncated: `corrupted_paths`
                    # must compare equal to the consumer's quarantine
                    # keys, which are full filesystem paths
                    self.events.append(
                        {**{k: (str(v) if k == "path"
                                else str(v)[:160])
                            for k, v in ctx.items()},
                         "point": point, "kind": spec.kind,
                         "occurrence": occ})
                    return spec
            return None

    def filter_scan(self, point: str, files: Sequence[tuple]
                    ) -> List[tuple]:
        """Delayed-visibility filter over `scan_published`-shaped
        ``(version, kind, path)`` tuples: the spec's `at`/`prob` decides
        PER DISTINCT FILE (in first-seen order) whether that file is
        hidden, and `arg` is how many subsequent scans it stays hidden."""
        specs = [s for s in self.specs
                 if s.point == point and s.kind == "delay_visibility"]
        if not specs:
            return list(files)
        with self._lock:
            visible = []
            for f in files:
                path = f[-1]
                hidden = False
                for spec in specs:
                    st = spec._delay
                    if path not in st["seen"]:
                        idx = st["next_idx"]
                        st["next_idx"] = idx + 1
                        st["seen"][path] = idx
                        if spec.budget_left() and spec.wants(idx):
                            spec.fires += 1
                            st["hiding"][path] = max(int(spec.arg), 1)
                            self.events.append(
                                {"point": point,
                                 "kind": "delay_visibility",
                                 "occurrence": idx, "path": path,
                                 "scans": int(spec.arg)})
                    rem = st["hiding"].get(path, 0)
                    if rem > 0:
                        st["hiding"][path] = rem - 1
                        hidden = True
                if not hidden:
                    visible.append(f)
            return visible

    # ---------------------------------------------------------- ledger
    def counts(self, point: Optional[str] = None,
               kind: Optional[str] = None) -> int:
        return sum(1 for e in self.events
                   if (point is None or e["point"] == point)
                   and (kind is None or e["kind"] == kind))

    def corrupted_paths(self, point: str = "store.publish") -> List[str]:
        """Final stream paths this plan corrupted on disk (the set a
        soak reconciles consumer quarantines against)."""
        return sorted({e["path"] for e in self.events
                       if e["point"] == point
                       and e["kind"] in CORRUPTING_KINDS and "path" in e})


# --------------------------------------------------------- global plumbing
_UNSET = object()
_active = _UNSET
_active_lock = threading.Lock()


def active_plan() -> Optional[FaultPlan]:
    """The installed plan: `set_plan`'s argument if one was set, else a
    plan parsed ONCE from ``DET_FAULT_PLAN`` (inline JSON / ``@path`` /
    path), else None. The common no-plan path is one attribute read."""
    global _active
    if _active is _UNSET:
        with _active_lock:
            if _active is _UNSET:
                env = os.environ.get("DET_FAULT_PLAN")
                _active = FaultPlan.from_json(env) if env else None
    return _active


def set_plan(plan: Optional[FaultPlan]) -> None:
    """Install `plan` process-wide (None = explicitly no faults,
    shadowing the env var until `reset_plan`)."""
    global _active
    with _active_lock:
        _active = plan


def reset_plan() -> None:
    """Forget any installed plan; the next `active_plan()` re-reads
    ``DET_FAULT_PLAN``."""
    global _active
    with _active_lock:
        _active = _UNSET


@contextmanager
def use_plan(plan: Optional[FaultPlan]):
    """Scoped install (tests / bench scenarios): restores the previous
    plan state on exit."""
    global _active
    with _active_lock:
        prev = _active
        _active = plan
    try:
        yield plan
    finally:
        with _active_lock:
            _active = prev


def check(point: str, **ctx) -> Optional[FaultSpec]:
    plan = active_plan()
    return plan.check(point, **ctx) if plan is not None else None


def check_raise(point: str, **ctx) -> Optional[FaultSpec]:
    """`check`, raising `InjectedIOError` when an ``io_error`` spec
    fires — the one-liner read seams use."""
    spec = check(point, **ctx)
    if spec is not None and spec.kind == "io_error":
        where = ctx.get("path") or ctx.get("stage") or ""
        raise InjectedIOError(
            f"{point}: injected transient IOError"
            + (f" ({where})" if where else ""))
    return spec


def filter_scan(point: str, files: Sequence[tuple]) -> List[tuple]:
    plan = active_plan()
    return plan.filter_scan(point, files) if plan is not None \
        else list(files)


def _payload_window(path: str) -> Tuple[int, int]:
    """(start, size) of the LAST non-metadata member's data region in a
    zip/npz file — the deterministic target region for injected damage
    (a flip in zip slack bytes like an extra field would be invisible to
    both the member CRCs and the container checksums: corruption that
    changes nothing is not a fault). Falls back to the whole file when
    the zip structure cannot be parsed."""
    try:
        import struct
        import zipfile
        with zipfile.ZipFile(path) as z:
            infos = [i for i in z.infolist()
                     if i.filename != "__meta__.npy"] or z.infolist()
            info = infos[-1]
        with open(path, "rb") as f:
            f.seek(info.header_offset + 26)
            fnlen, exlen = struct.unpack("<HH", f.read(4))
        start = info.header_offset + 30 + fnlen + exlen
        return start, max(int(info.compress_size), 1)
    except Exception:  # noqa: BLE001 - non-zip target: damage anywhere
        return 0, max(os.path.getsize(path), 1)


def corrupt_file(path: str, spec: FaultSpec) -> None:
    """Apply a write-corruption kind to a file on disk, deterministically:
    ``truncate`` cuts the file mid-payload at the ``arg`` fraction of
    the last member's data region; ``bit_flip`` XORs one bit at that
    offset — inside an array payload, exactly the damage the container
    checksums (and the zip member CRCs) must catch."""
    start, size = _payload_window(path)
    frac = float(spec.arg if spec.arg is not None else 0.5)
    off = start + min(max(int(size * frac), 0), size - 1)
    if spec.kind == "truncate":
        with open(path, "rb+") as f:
            f.truncate(max(off, 1))
    elif spec.kind == "bit_flip":
        with open(path, "rb+") as f:
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ 0x40]))
    else:
        raise ValueError(f"corrupt_file cannot apply kind {spec.kind!r}")
