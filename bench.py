"""Benchmark driver: synthetic 'tiny' model training step on one chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "ms", "vs_baseline": N}

Baseline: the reference's published single-GPU (A100-80GB) step time for the
synthetic Tiny model, global batch 65536, Adagrad: 24.433 ms
(BASELINE.md / reference examples/benchmarks/synthetic_models/README.md:69).
vs_baseline > 1 means faster than the reference, compared on throughput
(samples/sec) so a smaller batch — needed on a 16G-HBM chip vs the
reference's 80G A100 — still compares fairly.

Robustness: TPU backend init over the tunnel can fail transiently
(round-1 postmortem: a single UNAVAILABLE at init aborted the whole bench).
`_init_backend_with_retry` retries jax.devices() with backoff before giving
up, and OOM is detected by XlaRuntimeError/RESOURCE_EXHAUSTED status rather
than substring-matching arbitrary exception text.
"""

import functools
import os
import json
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from distributed_embeddings_tpu.models.synthetic import (
    SYNTHETIC_MODELS, SyntheticModel, InputGenerator)
from distributed_embeddings_tpu.training import make_sparse_train_step

BASELINE_TINY_1GPU_MS = 24.433
BASELINE_BATCH = 65536


def _probe_backend_subprocess(timeout_s: float) -> bool:
    """Probe device init in a THROWAWAY subprocess. Round-2 postmortem: a
    wedged tunnel claim makes jax.devices() HANG (not raise), so an
    in-process retry loop never regains control. A subprocess can be killed
    and retried; only when the probe succeeds do we init in-process."""
    import subprocess
    try:
        p = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); "
             "import jax.numpy as jnp; "
             "(jnp.ones((64, 64)) @ jnp.ones((64, 64))).block_until_ready(); "
             "print(d[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s)
        return p.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _init_backend_with_retry(attempts: int = 5, backoff_s: float = 30.0,
                             probe_timeout_s: float = 150.0):
    """Device init with hang-proof retry (see _probe_backend_subprocess).
    Returns the device list."""
    attempts = int(os.environ.get("DET_BENCH_INIT_ATTEMPTS", attempts))
    last_err = "backend probe timed out (wedged tunnel claim?)"
    for i in range(attempts):
        if _probe_backend_subprocess(probe_timeout_s):
            try:
                return jax.devices()
            except RuntimeError as e:
                last_err = str(e)[:300]
        print(f"backend init attempt {i + 1}/{attempts} failed: {last_err}",
              file=sys.stderr, flush=True)
        try:
            jax.extend.backend.clear_backends()
        except Exception:  # noqa: BLE001 - best-effort cache clear
            pass
        if i + 1 < attempts:
            time.sleep(backoff_s * (i + 1))
    raise RuntimeError(f"TPU backend unavailable after {attempts} attempts: "
                       f"{last_err}")


def _is_oom(e: Exception) -> bool:
    """True for genuine device OOM. Two shapes observed on hardware:
    an XLA runtime error with RESOURCE_EXHAUSTED status, and (round-2
    postmortem) a compile-time HBM overflow surfacing as INTERNAL from the
    remote-compile tunnel with the allocator report ('Ran out of memory in
    memory space hbm') in the message body."""
    is_xla_err = type(e).__name__ in ("XlaRuntimeError", "JaxRuntimeError")
    try:
        is_xla_err = is_xla_err or isinstance(e, jax.errors.JaxRuntimeError)
    except AttributeError:
        pass
    msg = str(e)
    return is_xla_err and ("RESOURCE_EXHAUSTED" in msg
                           or "Ran out of memory" in msg
                           or "Attempting to reserve" in msg)


def _slope_time_scan(step_fn, params, opt_state, batches, nb, iters,
                     profile_dir=None, span_path=None):
    """The scan/slope timing harness of record, shared by every bench.

    The whole measurement is ONE device program (lax.scan over `iters`
    steps, batches pre-staged on device), so per-dispatch tunnel latency
    cannot distort it.

    Sync + timing method (round-3 hardware finding): `block_until_ready` is
    NOT a reliable sync on the axon tunnel — it returned before device work
    finished and "measured" a step 63x faster than the HBM roofline. The
    sync of record is a host FETCH of the losses, which cannot complete
    before the data exists. The reported time is SLOPE-BASED: the program
    runs once (t1) then twice back-to-back (t2); per-step =
    (t2 - t1) / iters, cancelling constant dispatch/fetch/queue overhead
    (t2 should be ~2x t1 when constant overhead is small; a large
    deviation means the measurement is overhead- or queue-dominated).
    Both raw timings ride along in the returned dict.

    Returns (dt_seconds, warmup_losses, {t1_ms, t2_ms, iters}). The passed
    params/opt_state are DONATED — callers must not reuse them.

    `span_path` (ISSUE 14): open an obs span around ONLY the timed t1/t2
    runs — the attribution window for `--profile` modes. Deliberately
    excludes the warmup/compile run above it: a window that swallowed
    compile-time device ops would settle perf_model projections against
    numbers that are not steady-state step time.
    """
    @functools.partial(jax.jit, donate_argnums=(0, 1), static_argnums=(3,))
    def run_steps(params, opt_state, batches, n):
        def body(carry, i):
            params, opt_state = carry
            num, cats, labels = jax.tree.map(
                lambda x: jnp.take(x, i % nb, axis=0), batches)
            params, opt_state, loss = step_fn(params, opt_state, num,
                                              list(cats), labels)
            return (params, opt_state), loss
        (params, opt_state), losses = lax.scan(
            body, (params, opt_state), jnp.arange(n))
        return params, opt_state, losses

    def fetch(losses):
        """The real device sync: host fetch of the per-step losses."""
        arr = np.asarray(jax.device_get(losses))
        if not np.all(np.isfinite(arr)):
            raise RuntimeError(f"non-finite loss in benchmark: {arr}")
        return arr

    # warmup (compile) + queue drain
    params, opt_state, losses = run_steps(params, opt_state, batches, iters)
    warm = fetch(losses)
    if profile_dir:
        from distributed_embeddings_tpu.utils import profiling
        with profiling.trace(profile_dir):
            # rebind: donated params/opt_state are consumed by the call
            params, opt_state, losses = run_steps(params, opt_state,
                                                  batches, iters)
            fetch(losses)
        print(f"profiler trace written to {profile_dir}", file=sys.stderr)

    if span_path:
        from distributed_embeddings_tpu.obs import default_registry, span
        timed_cm = span(span_path, default_registry())
    else:
        import contextlib
        timed_cm = contextlib.nullcontext()
    with timed_cm:
        t0 = time.perf_counter()
        params, opt_state, losses = run_steps(params, opt_state, batches,
                                              iters)
        fetch(losses)
        t1 = time.perf_counter() - t0

        t0 = time.perf_counter()
        params, opt_state, losses = run_steps(params, opt_state, batches,
                                              iters)
        params, opt_state, losses = run_steps(params, opt_state, batches,
                                              iters)
        fetch(losses)
        t2 = time.perf_counter() - t0

    dt = max(t2 - t1, 1e-9) / iters
    return dt, warm, {"t1_ms": round(t1 * 1e3, 3),
                      "t2_ms": round(t2 * 1e3, 3), "iters": iters}


def run_at_batch(model, batch, iters=10, optimizer="adagrad"):
    """Steady-state step time via the shared scan/slope harness
    (`_slope_time_scan` holds the sync + timing method of record).

    Training uses the sparse tapped path (make_sparse_train_step): dense
    table grads for the 4.2 GiB tiny model would not fit 16G HBM and the
    full-table adagrad pass alone (~21 GiB traffic) exceeds the entire
    reference step budget.
    """
    params = model.init(jax.random.PRNGKey(0))
    init_fn, step_fn = make_sparse_train_step(model, optimizer, lr=0.01)
    opt_state = init_fn(params)
    gen = InputGenerator(model.config, batch, alpha=1.05, num_batches=2,
                         seed=0)
    batches = jax.tree.map(
        lambda *xs: jnp.stack(xs),
        *[(n, tuple(c), l) for (n, c, l) in gen.batches])
    dt, _, raw = _slope_time_scan(
        step_fn, params, opt_state, batches, len(gen), iters,
        profile_dir=os.environ.get("DET_BENCH_PROFILE"))
    run_at_batch.last_raw = raw
    return dt


def _git_sha() -> str:
    """HEAD sha at bench time: every record carries the code it measured
    (round-3 shipped a cached record that predated 15 perf commits —
    never again without it being visible)."""
    import subprocess
    try:
        return subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=os.path.dirname(
                os.path.abspath(__file__)), capture_output=True, text=True,
            timeout=10).stdout.strip()
    except Exception:  # noqa: BLE001
        return "unknown"


def _perf_files_changed_since(sha: str) -> int:
    """Number of files under ops/ or layers/ changed between `sha` and HEAD
    — nonzero means a cached record no longer describes this code."""
    import subprocess
    try:
        out = subprocess.run(
            ["git", "diff", "--name-only", sha, "HEAD", "--",
             "distributed_embeddings_tpu/ops",
             "distributed_embeddings_tpu/layers",
             "distributed_embeddings_tpu/training.py"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10)
        if out.returncode != 0:
            # unknown/garbage-collected sha, shallow clone: cannot
            # determine — must NOT read as "no changes"
            return -1
        return len([ln for ln in out.stdout.splitlines() if ln.strip()])
    except Exception:  # noqa: BLE001
        return -1


def run_ab_arm(extra: dict, key: str, env: dict, cfg, batch: int,
               iters: int, validate=None):
    """Run one A/B arm of the synthetic bench under `env` overrides.

    Records `{key}_ms` (and `{key}_valid` when a validator gates the arm,
    `{key}_error` on failure) into `extra`; returns the arm's step seconds
    or None when skipped/failed. The model is rebuilt per arm so env-
    dependent dispatch re-traces."""
    try:
        if validate is not None:
            valid = bool(validate())
            extra[f"{key}_valid"] = valid
            if not valid:
                return None
        for k, v in env.items():
            os.environ[k] = v
        dt = run_at_batch(SyntheticModel(cfg, mesh=None, distributed=True),
                          batch, iters=iters)
        extra[f"{key}_ms"] = round(dt * 1e3, 3)
        extra[f"{key}_raw"] = getattr(run_at_batch, "last_raw", None)
        return dt
    except Exception as e:  # noqa: BLE001 - an arm must not kill the bench
        extra[f"{key}_error"] = str(e)[:200]
        return None
    finally:
        for k in env:
            os.environ.pop(k, None)


# the real defaults-file location, resolved ONCE before _isolate_ below
# pins the env for the bench's own arms: writer and reader must agree on
# the path, including a user's DET_MEASURED_DEFAULTS_PATH override
_MEASURED_DEFAULTS_PATH = os.environ.get(
    "DET_MEASURED_DEFAULTS_PATH",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools",
                 "measured_defaults.json"))


def _isolate_from_measured_defaults() -> None:
    """The bench's A/B arms must measure exactly what their env says: a
    previously-written defaults file would silently flip the BASELINE arms
    too (tiled-vs-tiled 'A/B', self-contaminated evidence, unrevertable
    flips). Point the in-process reader at an unparsable path for the whole
    bench run; the writer still targets _MEASURED_DEFAULTS_PATH.

    ISSUE 18: the same contamination exists one layer up — a prior
    `--mode tune` run's tools/tuned/<workload>.json (or an operator's
    DET_TUNED_* env) would flip the baseline arms through the
    tune.resolve seam. Drop BOTH tuned selectors and reset the
    per-process resolution caches, so every arm resolves exactly
    env-override > fallback for the whole bench run."""
    os.environ["DET_MEASURED_DEFAULTS_PATH"] = os.devnull
    os.environ.pop("DET_TUNED_PATH", None)
    os.environ.pop("DET_TUNED_WORKLOAD", None)
    try:
        from distributed_embeddings_tpu.tune import resolve as _tune_resolve
        _tune_resolve.reset_cache()     # drop any cached tuned/measured read
    except Exception:  # noqa: BLE001
        pass


# minimum speedup of the tiled family over the best non-tiled arm, per
# workload, before a measured-defaults flip persists (ADVICE r5): a
# within-noise 1.001x "win" on one bench run must not change fleet-wide
# defaults. 3% clears the observed run-to-run jitter of the slope-based
# timing method with margin to spare.
MEASURED_DEFAULTS_MIN_MARGIN = 1.03

_AB_ARM_KEYS = {
    # per workload: (non-tiled arm ms keys, tiled arm ms keys, fwd+bwd key)
    # the ISSUE 12 fused arms count as NON-tiled competitors: a tiled
    # defaults flip must beat them too (flips to 'pallas' itself stay a
    # human decision until the kernels mode earns a TPU number)
    "tiny": (("tiny_ab_default_ms", "tiny_ab_pallas_ms", "tiny_ab_cumsum_ms",
              "tiny_ab_pallas_scatter_ms", "tiny_ab_pallas_fused_ms",
              "tiny_ab_pallas_fused_full_ms"),
             ("tiny_ab_tiled_ms", "tiny_ab_tiled_full_ms"),
             "tiny_ab_tiled_full_ms"),
    "dlrm": (("dlrm_ab_sort_ms", "dlrm_ab_cumsum_ms", "dlrm_ab_dense_ms"),
             ("dlrm_ab_tiled_ms", "dlrm_ab_tiled_full_ms"),
             "dlrm_ab_tiled_full_ms"),
}


def _tiled_margins(record: dict, workload: str):
    """(scatter_margin, lookup_margin) for one workload: how much faster the
    tiled family (resp. the full fwd+bwd tiled arm) ran than the best
    non-tiled arm. None where the needed timings are missing — a margin
    that cannot be computed must read as 'no flip', not 'any win'."""
    non_tiled_keys, tiled_keys, full_key = _AB_ARM_KEYS[workload]

    def best(keys):
        vals = [record.get(k) for k in keys]
        vals = [float(v) for v in vals if isinstance(v, (int, float)) and v > 0]
        return min(vals) if vals else None

    nt, t, full = best(non_tiled_keys), best(tiled_keys), best((full_key,))
    return (round(nt / t, 4) if nt and t else None,
            round(nt / full, 4) if nt and full else None)


def _maybe_write_measured_defaults(record: dict) -> None:
    """Decision rule 5 (docs/perf_model.md) executed by machinery: when the
    hardware A/B arms show the tiled kernel family winning on BOTH measured
    workloads (tiny AND dlrm — a missing workload means NO flip, not a
    weaker vote) by at least MEASURED_DEFAULTS_MIN_MARGIN on each, persist
    the winning knob values with provenance to the defaults file the
    library's TPU dispatch reads (sparse_update.measured_default). A tunnel
    window that lands while nobody is watching then flips user-facing
    defaults mechanically — but only on a margin that clears measurement
    noise, and the margin rides in the evidence block. Env vars still
    override at use time. DET_DEDUP_IMPL is deliberately NOT auto-flipped:
    cumsum trades ~sqrt(N)*eps precision and weakens the rep promise — a
    wall-clock win alone must not change numerics defaults."""
    if (jax.devices()[0].platform == "cpu"
            and os.environ.get("DET_BENCH_ALLOW_CPU_DEFAULTS_WRITE") != "1"):
        # CPU runs never flip fleet defaults; the override exists solely so
        # the unattended-window REHEARSAL (tools/window_rehearsal.py) can
        # execute this exact writer against a scratch defaults path
        return
    tiny_best = record.get("tiny_best_path", "")
    dlrm_best = record.get("dlrm_best_path", "")
    if not (tiny_best and dlrm_best):
        return                      # both workloads or no flip
    tiny_scatter, tiny_lookup = _tiled_margins(record, "tiny")
    dlrm_scatter, dlrm_lookup = _tiled_margins(record, "dlrm")

    def clears(*margins):
        return all(m is not None and m >= MEASURED_DEFAULTS_MIN_MARGIN
                   for m in margins)

    updates = {}
    if (tiny_best.startswith("tiled") and dlrm_best.startswith("tiled")
            and clears(tiny_scatter, dlrm_scatter)):
        updates["DET_SCATTER_IMPL"] = "tiled"
        if (tiny_best == "tiled-fwd+bwd" and dlrm_best == "tiled-fwd+bwd"
                and clears(tiny_lookup, dlrm_lookup)):
            updates["DET_LOOKUP_PATH"] = "tiled"
    if not updates:
        return
    path = _MEASURED_DEFAULTS_PATH
    try:
        with open(path) as f:
            data = json.load(f)
    except Exception:  # noqa: BLE001 - first write / invalid file
        data = {}
    evidence = {
        "tiny_best_path": tiny_best,
        "dlrm_best_path": dlrm_best,
        "tiny_ms": record.get("value"),
        "dlrm_samples_per_sec": record.get("dlrm_samples_per_sec"),
        "min_margin_required": MEASURED_DEFAULTS_MIN_MARGIN,
        "margins": {"tiny_scatter": tiny_scatter, "tiny_lookup": tiny_lookup,
                    "dlrm_scatter": dlrm_scatter, "dlrm_lookup": dlrm_lookup},
    }
    for k, v in updates.items():
        data[k] = {"value": v, "git_sha": record.get("git_sha"),
                   "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                time.gmtime()),
                   "evidence": evidence}
    with open(path, "w") as f:
        json.dump(data, f, indent=1)
    record["measured_defaults_written"] = updates


# ---------------------------------------------------------------- serving
def zipf_sampler(vocab: int, alpha: float, rng):
    """Power-law id sampler over [0, vocab): p(rank r) ~ r^-alpha — the
    classic recommender access skew the serving cache exploits."""
    ranks = np.arange(1, vocab + 1, dtype=np.float64)
    p = ranks ** -alpha
    p /= p.sum()
    return lambda n: rng.choice(vocab, size=n, p=p).astype(np.int32)


def run_serve_bench(requests: int = 128, batch: int = 64,
                    capacity: int = 1024, alpha: float = 1.2,
                    promote_threshold: int = 2, seed: int = 0,
                    updater_steps: int = 24, publish_every: int = 4,
                    train_batch: int = 64) -> dict:
    """Serving benchmark: InferenceEngine + MicroBatcher over a synthetic
    model with a host-offloaded bucket, fed a zipfian id stream of
    variable-size requests. Reports throughput, HBM-cache hit rate, batch
    occupancy and latency percentiles. Runs on any backend, including
    single-device CPU (the tier-1 smoke path).

    Concurrent-updater arm (ISSUE 6, on by default — `updater_steps=0`
    disables): a background thread trains a SECOND layer instance of the
    same plan on the same zipfian distribution and publishes row-delta
    files every `publish_every` steps through a `TableStore`
    (first publish = full snapshot); the serving loop polls and applies
    them BETWEEN request batches while the percentile clock runs. The
    record then measures the streaming path end to end: delta bytes vs
    one full table copy (`serve_delta_full_ratio` — the ≤ 10% claim at
    these touched-row rates), delta-apply row throughput, version/second
    staleness, version monotonicity, and final bit-exact parity between
    the consumer's tables and the publisher's
    (`serve_update_parity_max_dev`)."""
    from distributed_embeddings_tpu.layers.embedding import Embedding
    from distributed_embeddings_tpu.layers.dist_model_parallel import (
        DistributedEmbedding)
    from distributed_embeddings_tpu.serving import InferenceEngine, MicroBatcher
    from distributed_embeddings_tpu.store import TableStore

    rng = np.random.RandomState(seed)
    # one fused width-32 bucket; the 20k/8k tables blow a 16k-element budget
    specs = [(20000, 32), (8000, 32), (200, 32), (100, 32)]

    def build():
        return DistributedEmbedding(
            [Embedding(v, w, combiner="sum") for v, w in specs],
            gpu_embedding_size=16 * 1024)

    from distributed_embeddings_tpu.obs import default_registry
    obs_reg = default_registry()
    dist = build()
    if not dist._offload_enabled:
        return {"serve_error": "backend exposes no host memory space"}
    params = dist.init(jax.random.PRNGKey(seed))
    engine = InferenceEngine(dist, params, cache_capacity=capacity,
                             promote_threshold=promote_threshold,
                             registry=obs_reg)
    engine.warmup([batch])
    # warm-up batcher on a PRIVATE registry: the measurement batcher
    # below shares obs_reg's serve/request_seconds histogram, and the
    # cold-compile warm-up latencies must not enter the headline
    # percentiles (the reason the batcher is rebuilt at all)
    batcher = MicroBatcher(engine, max_batch=batch)
    samplers = [zipf_sampler(v, alpha, rng) for v, _ in specs]

    # ---- concurrent updater: second layer instance (same plan; separate
    # instance so the trainer's trace-time state never races the serving
    # forward's offload_lookup_scope), same starting weights
    updater = None
    if updater_steps > 0:
        import tempfile
        import threading
        from distributed_embeddings_tpu.training import (
            make_sparse_train_step)

        class _Tapped:
            def __init__(self, emb):
                self.embedding = emb

            def loss_fn(self, p, numerical, cats, labels, taps=None,
                        return_residuals=False):
                out = self.embedding(p["embedding"], list(cats), taps=taps,
                                     return_residuals=return_residuals)
                outs, res = out if return_residuals else (out, None)
                x = jnp.concatenate(
                    [o.reshape(o.shape[0], -1) for o in outs], axis=1)
                loss = jnp.mean(
                    (jnp.sum(x, axis=1) - labels.reshape(-1)) ** 2)
                return (loss, res) if return_residuals else loss

        t_dist = build()
        t_model = _Tapped(t_dist)
        t_params = {"embedding": t_dist.set_weights(
            dist.get_weights(engine.store.params))}
        init_fn, step_fn = make_sparse_train_step(t_model, "adagrad",
                                                  lr=0.05)
        t_state = init_fn(t_params)
        pub_store = TableStore(t_dist, t_params["embedding"],
                               t_state["emb"], registry=obs_reg)
        pub_dir = tempfile.mkdtemp(prefix="det_stream_")
        t_rng = np.random.RandomState(seed + 1)
        t_samplers = [zipf_sampler(v, alpha, t_rng) for v, _ in specs]
        pub_infos = []
        pub_err = []

        # first publish (the snapshot anchor) + consumer sync BEFORE the
        # clock: cold-start compile/copy must not pollute the percentiles
        pub_store.commit(t_params["embedding"], t_state["emb"])
        pub_infos.append(pub_store.publish(pub_dir))
        engine.poll_updates(pub_dir)

        def run_updater():
            nonlocal t_params, t_state
            try:
                for step in range(updater_steps):
                    cats = [jnp.asarray(s(train_batch).reshape(-1, 1))
                            for s in t_samplers]
                    labels = jnp.asarray(
                        t_rng.randn(train_batch).astype(np.float32))
                    pub_store.observe(cats)
                    t_params, t_state, _ = step_fn(
                        t_params, t_state, jnp.zeros((train_batch, 1)),
                        cats, labels)
                    if (step + 1) % publish_every == 0 \
                            or step + 1 == updater_steps:
                        pub_store.commit(t_params["embedding"],
                                         t_state["emb"])
                        pub_infos.append(pub_store.publish(pub_dir))
            except Exception as e:  # noqa: BLE001 - surfaced in the record
                pub_err.append(f"{type(e).__name__}: {e}")

        updater = threading.Thread(target=run_updater, daemon=True)

    def request():
        n = int(rng.randint(1, max(batch // 2, 2)))
        return [s(n) for s in samplers], n

    # warm the cache + compile everything off the clock, then measure with
    # a FRESH batcher so warm-up latencies never enter the percentiles
    for _ in range(4):
        batcher.submit(request()[0])
    batcher.flush()
    batcher = MicroBatcher(engine, max_batch=batch, registry=obs_reg)
    # steady-state hit rate: measure against a post-warm-up baseline so the
    # cold-start misses of the warm-up stream don't dilute the headline
    base = engine.cache_stats()
    h0, m0 = base["hits"], base["misses"]

    if updater is not None:
        updater.start()
    rows = 0
    last = None
    t0 = time.perf_counter()
    for i in range(requests):
        cats, n = request()
        batcher.submit(cats)
        rows += n
        if (i + 1) % 4 == 0:
            last = batcher.flush() or last
            if updater is not None:
                engine.poll_updates(pub_dir)   # async delta consumption
    last = batcher.flush() or last
    # fetch-sync on the last materialized result BEFORE stopping the clock
    # (async dispatch would otherwise inflate throughput; block_until_ready
    # lies on the tunnel, a host fetch does not)
    if last:
        jax.tree.map(lambda a: np.asarray(a), next(iter(last.values())))
    dt = max(time.perf_counter() - t0, 1e-9)
    s = batcher.summary()
    end = engine.cache_stats()
    lookups = (end["hits"] - h0) + (end["misses"] - m0)
    steady_hit_rate = round((end["hits"] - h0) / lookups, 4) if lookups else 0.0
    record = {
        "metric": "serve_synthetic_offload_zipf",
        "backend": jax.devices()[0].platform,
        "serve_requests": requests,
        "serve_rows": rows,
        "serve_batch": batch,
        "serve_cache_capacity": capacity,
        "serve_zipf_alpha": alpha,
        "serve_throughput_rows_per_sec": round(rows / dt),
        "serve_throughput_requests_per_sec": round(requests / dt, 1),
        "serve_hit_rate": steady_hit_rate,
        "serve_batch_occupancy": s["batch_occupancy"],
        "serve_queue_depth_max": s["queue_depth_max"],
        "serve_p50_ms": s["p50_ms"],
        "serve_p95_ms": s["p95_ms"],
        "serve_p99_ms": s["p99_ms"],
        "serve_cache": engine.cache_stats(),
        "git_sha": _git_sha(),
    }
    if updater is not None:
        updater.join()
        engine.poll_updates(pub_dir)    # drain whatever published last
        ustats = engine.update_stats(pub_dir)
        # final parity: the consumer's merged tables must equal the
        # publisher's bit for bit at the drained version
        dev = 0.0
        for a, b in zip(pub_store.get_weights(),
                        engine.store.get_weights()):
            dev = max(dev, float(np.max(np.abs(a - b))))
        deltas = [i for i in pub_infos if i["kind"] == "delta"]
        full_bytes = pub_store.full_table_bytes()
        d_mean = (float(np.mean([i["bytes"] for i in deltas]))
                  if deltas else 0.0)
        record.update({
            "serve_updater_steps": updater_steps,
            "serve_publish_every": publish_every,
            "serve_train_batch": train_batch,
            "serve_updates_published": len(pub_infos),
            "serve_updates_applied": ustats.get("applied", 0),
            # the DELTA count is the streaming-path gate: the pre-clock
            # snapshot sync alone must never satisfy it
            "serve_updates_applied_deltas": ustats.get("applied_deltas", 0),
            "serve_full_table_bytes": full_bytes,
            "serve_delta_bytes_mean": int(d_mean),
            "serve_delta_bytes_total": int(sum(i["bytes"]
                                               for i in deltas)),
            "serve_delta_rows_mean": (int(np.mean([i["rows"]
                                                   for i in deltas]))
                                      if deltas else 0),
            # the ≤ 10% acceptance number: mean delta bytes per publish
            # over one full-table copy, at this workload's touched rates
            "serve_delta_full_ratio": round(d_mean / full_bytes, 5),
            "serve_delta_apply_rows_per_sec":
                ustats.get("apply_rows_per_sec", 0),
            "serve_staleness_versions_max":
                ustats.get("staleness_versions_max", 0),
            "serve_staleness_versions_mean":
                ustats.get("staleness_versions_mean", 0.0),
            "serve_staleness_s_max": ustats.get("staleness_s_max", 0.0),
            "serve_staleness_s_mean": ustats.get("staleness_s_mean", 0.0),
            "serve_version_monotonic": ustats.get("version_monotonic",
                                                  False),
            "serve_update_parity_max_dev": dev,
        })
        if pub_err:
            record["serve_updater_error"] = pub_err[0][:300]
        import shutil
        shutil.rmtree(pub_dir, ignore_errors=True)   # snapshots are MBs
    return record


def serve_main(argv=None) -> int:
    """`bench.py --mode serve` entry point: one JSON line, like main()."""
    import argparse
    p = argparse.ArgumentParser(description="serving benchmark")
    p.add_argument("--mode", choices=["serve"], default="serve")
    p.add_argument("--requests", type=int, default=128)
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--capacity", type=int, default=1024)
    p.add_argument("--alpha", type=float, default=1.2)
    p.add_argument("--promote_threshold", type=int, default=2)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--updater_steps", type=int, default=24,
                   help="concurrent train-publish-consume arm (ISSUE 6): "
                        "background training steps; 0 disables")
    p.add_argument("--publish_every", type=int, default=4)
    p.add_argument("--train_batch", type=int, default=64)
    _add_profile_arg(p)
    args = p.parse_args(argv)
    if os.environ.get("DET_BENCH_FORCE_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    record = _run_with_device_attribution(
        lambda: run_serve_bench(
            requests=args.requests, batch=args.batch,
            capacity=args.capacity, alpha=args.alpha,
            promote_threshold=args.promote_threshold, seed=args.seed,
            updater_steps=args.updater_steps,
            publish_every=args.publish_every,
            train_batch=args.train_batch),
        args.profile)
    print(json.dumps(_stamp_metrics_snapshot(_stamp_audit_findings(record))))
    return 0 if "serve_error" not in record else 1


def _load_hlo_audit():
    """Load tools/hlo_audit.py by path (it is a script, not a package
    module) — shared by the main bench's per-record audit and the hotrows
    A/B gate."""
    import importlib.util as _ilu
    _sp = _ilu.spec_from_file_location(
        "det_hlo_audit", os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tools",
            "hlo_audit.py"))
    _ha = _ilu.module_from_spec(_sp)
    _sp.loader.exec_module(_ha)
    return _ha


def _stamp_audit_findings(record: dict) -> dict:
    """Stamp the static auditor's verdict onto a bench record before it
    is emitted (ISSUE 10): ``audit_findings`` = count + stable finding
    ids over the standard program matrix (tools/hlo_audit.py), EMPTY on
    green — so every BENCH_*.json replay carries the audit state of the
    code it was measured under, the same way records already carry
    ``hlo_sort_audit`` fingerprints. Never raises: a host that cannot
    lower the matrix (e.g. < 8 devices) records the error instead.
    Cached tunnel-down replays are NOT re-stamped — they keep the state
    they were measured under."""
    try:
        # the matrix needs a multi-device mesh to lower real
        # collectives; scale to what this host has (>= 2) rather than
        # demanding the audit driver's 8-virtual-CPU world — the plan
        # contexts are computed from the actual plan, so the invariants
        # stay exact at any world size
        world = min(8, len(jax.devices()))
        if world < 2:
            record["audit_findings"] = {
                "error": "needs >= 2 devices to lower the meshed "
                         "program matrix"}
            return record
        _ha = _load_hlo_audit()
        recs, _ = _ha.run_matrix(_ha.load_baseline(), world=world)
        ids = sorted({f"{r['program']}:{f['fid']}"
                      for r in recs for f in r["findings"]})
        record["audit_findings"] = {"count": len(ids), "ids": ids,
                                    "world": world}
    except Exception as e:  # noqa: BLE001 - audit must not kill bench
        record["audit_findings"] = {"error": str(e)[:200]}
    return record


def _stamp_metrics_snapshot(record: dict) -> dict:
    """Stamp the process-default `obs.MetricRegistry` snapshot onto a
    bench record before it is emitted (ISSUE 11): every mode wires its
    components (engine, batcher, store, vocab manager, lookahead
    engine, merged ingest histograms) onto `obs.default_registry()`, so
    ``metrics_snapshot`` carries the run's full telemetry next to
    ``audit_findings``. With ``DET_SLO_RULES=<file>`` the snapshot is
    additionally evaluated against the checked-in SLO rules and the
    findings land as ``slo_findings`` ({"count", "ids"} — the
    audit-findings shape, gated the same way). Never raises."""
    try:
        from distributed_embeddings_tpu.obs import registry as obs_registry
        record["metrics_snapshot"] = obs_registry.default_registry(
        ).snapshot()
    except Exception as e:  # noqa: BLE001 - telemetry must not kill bench
        record["metrics_snapshot"] = {"error": str(e)[:200]}
        return record
    rules_path = os.environ.get("DET_SLO_RULES")
    if rules_path:
        try:
            from distributed_embeddings_tpu.obs import slo
            record["slo_findings"] = slo.summarize(slo.evaluate_rules(
                slo.load_rules(rules_path), record["metrics_snapshot"]))
        except Exception as e:  # noqa: BLE001 - a bad rule FILE is an
            # error stamp, never a lost snapshot
            record["slo_findings"] = {"error": str(e)[:200]}
        pm_dir = os.environ.get("DET_OBS_POSTMORTEM_DIR")
        if pm_dir and record["slo_findings"].get("count"):
            # an SLO breach is an incident (ISSUE 14): dump the flight
            # recorder + snapshot exactly like a degraded entry would
            try:
                from distributed_embeddings_tpu import obs
                record["slo_postmortem"] = obs.dump_postmortem(
                    pm_dir, "slo_breach",
                    registry=obs.default_registry(),
                    extra={"slo_findings": record["slo_findings"],
                           "metric": record.get("metric")})
            except Exception as e:  # noqa: BLE001 - artifact only
                record["slo_postmortem"] = f"error: {str(e)[:200]}"
    return record


def _run_with_device_attribution(run_fn, enabled: bool) -> dict:
    """Run one bench mode under a jax profiler capture and stamp the
    ``device_attribution`` block onto its record (ISSUE 14,
    ``--profile``): per-span device seconds attributed from the
    capture's chrome trace to the obs span annotations the mode opened,
    plus the unattributed remainder — the two sum to the total device
    time by construction — and the collective-exposure breakdown. The
    ``device/*`` gauges land on the default registry, so the record's
    ``metrics_snapshot`` carries them too. Mode-specific reconciliation
    (the kernels projections table, the lookahead exposed-exchange
    stamp) happens in the mode mains, where the arm<->span mapping and
    per-step normalization are known.

    Attribution failures never lose the record (an ``error`` stamp
    rides instead); a failure in the RUN propagates exactly as it
    would unprofiled."""
    if not enabled:
        return run_fn()
    import shutil
    import tempfile

    from distributed_embeddings_tpu.utils import profiling
    logdir = tempfile.mkdtemp(prefix="det_bench_profile_")
    try:
        # python tracer OFF: a bench run's per-python-call events
        # overflow the profiler's host buffer and silently drop the
        # late span annotations attribution needs (see profiling.trace)
        with profiling.trace(logdir, python_tracer_level=0):
            record = run_fn()
        try:
            from distributed_embeddings_tpu import obs
            record["device_attribution"] = obs.attribution.attribute_logdir(
                logdir, registry=obs.default_registry())
        except Exception as e:  # noqa: BLE001 - keep the record
            record["device_attribution"] = {"error": str(e)[:300]}
        return record
    finally:
        shutil.rmtree(logdir, ignore_errors=True)


# kernels_tpu_projections key -> (bench span, how its device seconds
# normalize to the projection's per-step/per-call ms). The fwd spans
# time 3 forward replays; the step spans time 3*iters scanned steps
# (_slope_time_scan's t1 + t2 runs). Keys mapping to None are
# projections no current span isolates (the fused bwd+opt share the
# step span with the forward) — they stay "unmeasured" rather than
# reconciling against a number that is not theirs.
_KERNELS_PROJECTION_ARMS = {
    "dlrm_step_ms": ("bench/kernels/step/pallas", "step"),
    "dlrm_step_ms_measured_xla": ("bench/kernels/step/sort", "step"),
    "dlrm_fused_fwd_ms": ("bench/kernels/fwd/fused", "fwd"),
    "dlrm_fused_bwd_opt_ms": None,
    "tiny_fused_fwd_ms": ("bench/kernels/fwd/fused", "fwd"),
    "tiny_fused_fwd_ms_measured": ("bench/kernels/fwd/xla", "fwd"),
    "tiny_fused_bwd_opt_ms": None,
    "tiny_bwd_opt_ms_measured_xla_sort": None,
}


def _kernels_reconcile(record: dict, iters: int,
                       tolerance_frac: float = 0.5) -> None:
    """Build the kernels measured-vs-projection table (ISSUE 14) from
    the attribution's per-arm spans: device seconds normalize to
    per-step (span timed 3*iters scanned steps) or per-forward-call
    (span timed 3 replays) milliseconds, then settle/falsify each
    `kernels_tpu_projections` row through `_KERNELS_PROJECTION_ARMS`.

    Honesty rails: on CPU every verdict is "unmeasured" (interpret-mode
    arms are structural evidence only — `kernels_cpu_note`), and even
    on hardware a verdict only MEANS something when the invocation ran
    the projection's reference shape; the note says so and the
    normalized `per_arm_device_ms` ride along for any-shape reading."""
    att = record.get("device_attribution")
    proj = record.get("kernels_tpu_projections")
    if not isinstance(att, dict) or "spans" not in att \
            or not isinstance(proj, dict):
        return
    spans = att["spans"]
    per_arm = {}
    for path, seconds in spans.items():
        if path.startswith("bench/kernels/fwd/"):
            per_arm[path] = round(seconds * 1e3 / 3, 3)
        elif path.startswith("bench/kernels/step/"):
            per_arm[path] = round(seconds * 1e3 / (3 * max(iters, 1)), 3)
    att["per_arm_device_ms"] = per_arm
    cpu = record.get("backend") == "cpu"
    rows = []
    for phase, projected_ms in sorted(proj.items()):
        entry = _KERNELS_PROJECTION_ARMS.get(phase)
        measured = per_arm.get(entry[0]) if entry else None
        if cpu or measured is None:
            verdict = "unmeasured"
        else:
            rel = (abs(measured - float(projected_ms))
                   / max(abs(float(projected_ms)), 1e-9))
            verdict = "settled" if rel <= tolerance_frac else "falsified"
        rows.append({"phase": phase, "projected_ms": projected_ms,
                     "measured_ms": measured,
                     "arm_span": entry[0] if entry else None,
                     "verdict": verdict})
    att["reconciliation"] = rows
    att["reconciliation_note"] = (
        "CPU interpret arms are structural evidence only — every row "
        "unmeasured by policy (kernels_cpu_note)" if cpu else
        "verdicts are meaningful only when this invocation ran the "
        "projection's reference shape (docs/perf_model.md 'Fused "
        "sparse path'); per_arm_device_ms carries the normalized "
        "measurements for any-shape reading")


def _add_profile_arg(parser) -> None:
    parser.add_argument(
        "--profile", action="store_true",
        help="capture a jax profiler trace around the run and stamp the "
             "device_attribution block (per-span device seconds, "
             "unattributed remainder, collective exposure) into the "
             "record — every tunnel-window arm runs with this on "
             "(docs/perf_model.md)")


# --------------------------------------------------------------- hotrows
def run_hotrows_bench(vocab: int = 2_000_000, width: int = 128,
                      batch: int = 65536, hotness: int = 1,
                      alpha: float = 1.05, hot_rows: int = 16384,
                      iters: int = 10, warmup_batches: int = 4,
                      optimizer: str = "adagrad", seed: int = 0) -> dict:
    """Hot-row replication A/B (ISSUE 4): the tapped sparse train step on
    one zipfian single-table workload, with and without the training-side
    hot-row shard (`DistributedEmbedding(hot_rows=...)`).

    Arms share weights, data and timing method (scanned multi-step
    program, slope-timed, loss-fetch-synced — see run_at_batch). The hot
    arm observes `warmup_batches` batches, admits the hottest rows via
    `sync_hot_rows(admit=True)`, then times the steady-state step; the
    measured hot-shard hit rate of the TIMED id stream and the loss
    deviation between arms ride in the record. Runs on any backend
    (CPU smoke shapes via flags; perf numbers only mean something on
    hardware)."""
    from distributed_embeddings_tpu.layers.embedding import Embedding
    from distributed_embeddings_tpu.layers.dist_model_parallel import (
        DistributedEmbedding)

    rng = np.random.RandomState(seed)

    class _Tapped:
        def __init__(self, hot):
            self.embedding = DistributedEmbedding(
                [Embedding(vocab, width, combiner="sum")], mesh=None,
                hot_rows=hot)

        def loss_fn(self, p, numerical, cats, labels, taps=None,
                    return_residuals=False):
            out = self.embedding(p["embedding"], list(cats), taps=taps,
                                 return_residuals=return_residuals)
            outs, res = out if return_residuals else (out, None)
            x = outs[0].reshape(outs[0].shape[0], -1)
            loss = jnp.mean((jnp.sum(x, axis=1) - labels.reshape(-1)) ** 2)
            return (loss, res) if return_residuals else loss

    def zipf_ids(n):
        # hash-and-mod fold into the vocab (same idiom as the ingest
        # bench's key synth / examples/criteo): clamping instead would
        # alias the ENTIRE >= vocab tail (41-56% of draws at alpha~1.05)
        # onto the single id vocab-1, fabricating one super-hot row and
        # overstating the measured hit rate the A/B reports
        z = rng.zipf(alpha, size=n).astype(np.int64)
        return (z * 2654435761 % (1 << 40) % vocab).astype(np.int32)

    nb = 2
    data_batches = [
        (np.zeros((batch, 1), np.float32),
         (zipf_ids((batch, hotness)),),
         rng.randn(batch).astype(np.float32))
        for _ in range(nb)]
    warm_batches = [(zipf_ids((batch, hotness)),)
                    for _ in range(warmup_batches)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs),
                           *[(jnp.asarray(n), tuple(map(jnp.asarray, c)),
                              jnp.asarray(l))
                             for (n, c, l) in data_batches])

    def time_arm(hot, record, key):
        model = _Tapped(hot)
        emb = model.embedding
        params = {"embedding": emb.init(jax.random.PRNGKey(seed))}
        init_fn, step_fn = make_sparse_train_step(model, optimizer, lr=0.01)
        opt_state = init_fn(params)
        hit_rate, resident = None, 0
        if hot:
            for (c,) in warm_batches:
                emb.observe_hot_ids([c])
            p, s = emb.sync_hot_rows(params["embedding"], opt_state["emb"],
                                     admit=True)
            params = {"embedding": p}
            opt_state = {**opt_state, "emb": s}
            # measured hit rate of the TIMED stream vs the admitted set
            trs = list(emb._hot_trackers.values())
            h0 = sum(t.hits for t in trs)
            m0 = sum(t.misses for t in trs)
            for (_, c, _) in data_batches:
                emb.observe_hot_ids(list(c))
            h1 = sum(t.hits for t in trs)
            m1 = sum(t.misses for t in trs)
            seen = (h1 - h0) + (m1 - m0)
            hit_rate = round((h1 - h0) / seen, 4) if seen else 0.0
            resident = sum(t.resident for t in trs)

        dt, first_losses, raw = _slope_time_scan(
            step_fn, params, opt_state, stacked, nb, iters)
        record[f"{key}_ms"] = round(dt * 1e3, 3)
        record[f"{key}_raw"] = raw
        return dt, first_losses, hit_rate, resident, emb

    record = {
        "metric": "hotrows_zipf_train_ab",
        "backend": jax.devices()[0].platform,
        "hotrows_vocab": vocab, "hotrows_width": width,
        "hotrows_batch": batch, "hotrows_hotness": hotness,
        "hotrows_alpha": alpha, "hotrows_capacity": hot_rows,
        "hotrows_optimizer": optimizer, "hotrows_iters": iters,
        "git_sha": _git_sha(),
    }
    dt_base, losses_base, _, _, _ = time_arm(0, record, "hotrows_base")
    dt_hot, losses_hot, hit_rate, resident, emb = time_arm(
        hot_rows, record, "hotrows_hot")
    record["hotrows_hit_rate"] = hit_rate
    record["hotrows_resident"] = resident
    # slope timing degenerates when t2-t1 is below timer noise (tiny CI
    # shapes): a speedup computed from a clamped denominator is
    # meaningless — report 0.0 and let the raw t1/t2 tell the story
    reliable = dt_base > 1e-6 and dt_hot > 1e-6
    record["hotrows_speedup"] = (round(dt_base / dt_hot, 3)
                                 if reliable else 0.0)
    # the arms see identical data from the same init: the warm-up-scan
    # losses must agree to float tolerance (full parity lives in
    # tests/test_hotrows.py; this is the bench-side sanity marker)
    n = min(len(losses_base), len(losses_hot))
    record["hotrows_loss_max_dev"] = float(
        np.max(np.abs(losses_base[:n] - losses_hot[:n])))
    rep = emb.exchange_padding_report(hotness=[hotness])
    record["hotrows_padding_report"] = {
        "hot_hit_ids": rep["hot_hit_ids"],
        "true_ids_post_hot": rep["true_ids_post_hot"],
        "hot_hit_rates": {str(k): round(v, 4)
                          for k, v in rep["hot_hit_rates"].items()},
        # exchange byte accounting (ISSUE 5 backfill): wire formats +
        # id/activation bytes per sample, so hot-row records carry the
        # same statically auditable wire fields as --mode wire
        "exchanged_bytes": rep["exchanged_bytes"],
        "true_bytes": rep["true_bytes"],
        "act_bytes": rep["act_bytes"],
        "act_bytes_f32": rep["act_bytes_f32"],
        "act_wire_reduction": round(rep["act_wire_reduction"], 3),
        "wire_dtypes": {str(k): v for k, v in rep["wire_dtypes"].items()},
        "id_narrowed_groups": rep["id_narrowed_groups"]}
    # gate: the hot split adds ZERO sort instructions per exchange group
    # (searchsorted membership + dense replicated update; see
    # tools/hlo_audit.py) — lowering-only, tunnel-safe
    try:
        _ha = _load_hlo_audit()
        base_a = _ha.audit_tapped_step(optimizer=optimizer, strategy="sort",
                                       hotness=hotness, hot_rows=0)
        hot_a = _ha.audit_tapped_step(optimizer=optimizer, strategy="sort",
                                      hotness=hotness, hot_rows=hot_rows)
        record["hlo_sort_audit"] = [base_a, hot_a]
        record["hotrows_extra_sorts"] = (hot_a["hlo_sort"]
                                         - base_a["hlo_sort"])
    except Exception as e:  # noqa: BLE001 - audit must not kill the bench
        record["hlo_sort_audit_error"] = str(e)[:200]
    return record


def hotrows_main(argv=None) -> int:
    """`bench.py --mode hotrows` entry point: one JSON line, like main()."""
    import argparse
    p = argparse.ArgumentParser(description="hot-row replication benchmark")
    p.add_argument("--mode", choices=["hotrows"], default="hotrows")
    p.add_argument("--vocab", type=int, default=2_000_000)
    p.add_argument("--width", type=int, default=128)
    p.add_argument("--batch", type=int, default=65536)
    p.add_argument("--hotness", type=int, default=1)
    p.add_argument("--alpha", type=float, default=1.05)
    p.add_argument("--hot_rows", type=int, default=16384)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--warmup_batches", type=int, default=4)
    p.add_argument("--optimizer", default="adagrad",
                   choices=["sgd", "adagrad", "adam"])
    p.add_argument("--seed", type=int, default=0)
    _add_profile_arg(p)
    args = p.parse_args(argv)
    if os.environ.get("DET_BENCH_FORCE_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    try:
        record = _run_with_device_attribution(
            lambda: run_hotrows_bench(
                vocab=args.vocab, width=args.width, batch=args.batch,
                hotness=args.hotness, alpha=args.alpha,
                hot_rows=args.hot_rows, iters=args.iters,
                warmup_batches=args.warmup_batches,
                optimizer=args.optimizer, seed=args.seed),
            args.profile)
    except Exception as e:  # noqa: BLE001 - one JSON line, like main()
        import traceback
        traceback.print_exc()
        record = {"metric": "hotrows_zipf_train_ab",
                  "hotrows_error": str(e)[:300], "git_sha": _git_sha()}
    print(json.dumps(_stamp_metrics_snapshot(_stamp_audit_findings(record))))
    return 0 if "hotrows_error" not in record else 1


# ----------------------------------------------------------------- vocab
def run_vocab_bench(steps: int = 64, batch: int = 4096, tables: int = 4,
                    vocab: int = 50_000, slack: int = 8192,
                    width: int = 32, alpha: float = 1.2,
                    drift_every: int = 8, drift_frac: float = 0.25,
                    admit_threshold: int = 2, decay: float = 0.98,
                    vocab_every: int = 4, optimizer: str = "adagrad",
                    seed: int = 0) -> dict:
    """Dynamic-vocabulary benchmark (ISSUE 7): a zipfian RAW-key stream
    whose key universe ROTATES (every `drift_every` steps a uniformly
    random `drift_frac` of the rank space re-bases onto fresh raw keys
    — under the zipf skew that is mostly tail churn with a steady
    trickle of head turnover, the 'new users arriving, old users
    churning' drift a production recommender sees) drives a real
    sparse training loop through a `VocabManager`. Records admission/eviction rates, steady-state
    occupancy, fallback-hit rate, the host-side translate/maintain cost,
    and the compile count of the jitted step across the whole run (the
    recompile-free-growth claim: it must be 1 per batch shape).

    The structural acceptance is drift WITHOUT unbounded growth:
    `vocab_occupancy_max` stays <= the manager's high watermark while
    admissions and evictions both keep happening."""
    from distributed_embeddings_tpu.layers.embedding import Embedding
    from distributed_embeddings_tpu.layers.dist_model_parallel import (
        DistributedEmbedding)
    from distributed_embeddings_tpu.vocab import VocabManager

    rng = np.random.RandomState(seed)
    specs = [(vocab, width)] * tables
    emb = DistributedEmbedding(
        [Embedding(v, w, combiner="sum") for v, w in specs],
        vocab_slack=slack)

    class _M:
        def __init__(self):
            self.embedding = emb

        def loss_fn(self, p, numerical, cats, labels, taps=None,
                    return_residuals=False):
            out = emb(p["embedding"], list(cats), taps=taps,
                      return_residuals=return_residuals)
            outs, res = out if return_residuals else (out, None)
            x = jnp.concatenate([o.reshape(o.shape[0], -1) for o in outs],
                                axis=1)
            loss = jnp.mean((jnp.sum(x, axis=1)
                             - labels.reshape(-1)) ** 2)
            return (loss, res) if return_residuals else loss

    from distributed_embeddings_tpu.obs import default_registry
    model = _M()
    mgr = VocabManager(emb, admit_threshold=admit_threshold, decay=decay,
                       registry=default_registry())
    init_fn, step_fn = make_sparse_train_step(model, optimizer, lr=0.05)
    params = {"embedding": emb.init(jax.random.PRNGKey(seed))}
    state = init_fn(params)
    step = jax.jit(step_fn, donate_argnums=())

    sample = zipf_sampler(vocab, alpha, rng)
    # rotating raw-key universe: rank r of epoch e maps to a raw key
    # that changes for the rotated band each drift epoch
    epoch_of_rank = np.zeros((vocab,), np.int64)
    n_rot = max(int(vocab * drift_frac), 1)

    def raw_keys(n):
        ranks = sample(n).astype(np.int64)
        return (ranks + 10**9 * (1 + epoch_of_rank[ranks])).astype(np.int64)

    occ_max = 0.0
    translate_s, maintain_s, step_s = [], [], []
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        for i in range(steps):
            if i and drift_every and i % drift_every == 0:
                band = rng.choice(vocab, size=n_rot, replace=False)
                epoch_of_rank[band] += 1          # those ranks = NEW keys
            cats_raw = [raw_keys(batch).reshape(batch, 1)
                        for _ in range(tables)]
            # maintain BEFORE translating (fit's ordering): a rebind in
            # the cycle must be visible to this batch's translation
            if i and vocab_every and i % vocab_every == 0:
                t0 = time.perf_counter()
                p_emb, s_emb = mgr.maintain(params["embedding"],
                                            state["emb"])
                params = {**params, "embedding": p_emb}
                state = {**state, "emb": s_emb}
                maintain_s.append(time.perf_counter() - t0)
                occ = mgr.stats()["occupancy"]
                occ_max = max(occ_max, occ)
            t0 = time.perf_counter()
            cats = mgr.translate(cats_raw, observe=True)
            translate_s.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            params, state, loss = step(
                params, state, jnp.zeros((batch, 1)),
                [jnp.asarray(c) for c in cats],
                jnp.zeros((batch,), jnp.float32))
            jax.block_until_ready(loss)
            step_s.append(time.perf_counter() - t0)
    st = mgr.stats()
    cycles = max(st["maintain_cycles"], 1)
    rep = emb.exchange_padding_report(vocab=mgr)
    return {
        "metric": "vocab_zipf_drift_admission",
        "vocab_steps": steps,
        "vocab_batch": batch,
        "vocab_tables": tables,
        "vocab_rows": vocab,
        "vocab_slack": slack,
        "vocab_alpha": alpha,
        "vocab_drift_every": drift_every,
        "vocab_drift_frac": drift_frac,
        "vocab_admit_threshold": admit_threshold,
        "vocab_decay": decay,
        "vocab_admissions": st["admissions"],
        "vocab_evictions": st["evictions"],
        "vocab_admission_rate_per_step": round(st["admissions"] / steps, 3),
        "vocab_eviction_rate_per_step": round(st["evictions"] / steps, 3),
        "vocab_admissions_per_cycle": round(st["admissions"] / cycles, 3),
        "vocab_occupancy": st["occupancy"],
        "vocab_occupancy_max": round(occ_max, 4),
        "vocab_high_watermark": mgr.high_watermark,
        "vocab_fallback_hit_rate": st["fallback_hit_rate"],
        "vocab_bound_rows": st["bound"],
        "vocab_report_occupancy": rep["occupancy"],
        "vocab_report_slack_rows": rep["slack_rows"],
        "vocab_report_evictions_per_step": rep["evictions_per_step"],
        "vocab_step_compiles": step._cache_size(),
        "vocab_translate_ms_mean": round(
            1e3 * float(np.mean(translate_s)), 3),
        "vocab_maintain_ms_mean": round(
            1e3 * float(np.mean(maintain_s)), 3) if maintain_s else 0.0,
        "vocab_step_ms_mean": round(1e3 * float(np.mean(step_s)), 3),
        "vocab_samples_per_sec": round(
            batch / float(np.mean(step_s[len(step_s) // 2:]))),
        "git_sha": _git_sha(),
    }


def vocab_main(argv=None) -> int:
    """`bench.py --mode vocab` entry point: one JSON line, like main()."""
    import argparse
    p = argparse.ArgumentParser(description="dynamic vocabulary benchmark")
    p.add_argument("--mode", choices=["vocab"], default="vocab")
    p.add_argument("--steps", type=int, default=64)
    p.add_argument("--batch", type=int, default=4096)
    p.add_argument("--tables", type=int, default=4)
    p.add_argument("--vocab", type=int, default=50_000)
    p.add_argument("--slack", type=int, default=8192)
    p.add_argument("--width", type=int, default=32)
    p.add_argument("--alpha", type=float, default=1.2)
    p.add_argument("--drift_every", type=int, default=8)
    p.add_argument("--drift_frac", type=float, default=0.25)
    p.add_argument("--admit_threshold", type=int, default=2)
    p.add_argument("--decay", type=float, default=0.98)
    p.add_argument("--vocab_every", type=int, default=4)
    p.add_argument("--optimizer", default="adagrad",
                   choices=["sgd", "adagrad", "adam"])
    p.add_argument("--seed", type=int, default=0)
    _add_profile_arg(p)
    args = p.parse_args(argv)
    if os.environ.get("DET_BENCH_FORCE_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    try:
        record = _run_with_device_attribution(
            lambda: run_vocab_bench(
                steps=args.steps, batch=args.batch, tables=args.tables,
                vocab=args.vocab, slack=args.slack, width=args.width,
                alpha=args.alpha, drift_every=args.drift_every,
                drift_frac=args.drift_frac,
                admit_threshold=args.admit_threshold, decay=args.decay,
                vocab_every=args.vocab_every, optimizer=args.optimizer,
                seed=args.seed),
            args.profile)
    except Exception as e:  # noqa: BLE001 - one JSON line, like main()
        import traceback
        traceback.print_exc()
        record = {"metric": "vocab_zipf_drift_admission",
                  "vocab_error": str(e)[:300], "git_sha": _git_sha()}
    print(json.dumps(_stamp_metrics_snapshot(_stamp_audit_findings(record))))
    return 0 if "vocab_error" not in record else 1


# ------------------------------------------------------------------ wire
def run_wire_bench(vocab: int = 100_000, width: int = 128, tables: int = 8,
                   batch: int = 8192, hotness: int = 1, world: int = 8,
                   iters: int = 5, optimizer: str = "adagrad",
                   wire: str = "bf16", seed: int = 0) -> dict:
    """Wire-compression A/B (ISSUE 5): the tapped sparse train step over a
    `world`-device mesh at the DLRM-ish shape, f32 vs compressed exchange
    wire (`DistributedEmbedding(exchange_wire=...)`).

    Arms share weights, data and the timing method of record (scanned
    multi-step program, slope-timed, loss-fetch-synced — see
    `_slope_time_scan`). The record carries: both step times, the
    warm-up-loss parity marker between arms (bf16 rounds ONE cast per
    wire crossing, so losses agree to bf16 tolerance, never bit-exactly),
    the static byte accounting from `exchange_padding_report`, and the
    compiled-HLO collective-byte audit of both lowered steps (the
    `tools/hlo_audit.py` wire arm) — so the halved-wire claim is
    auditable from this one JSON line. Runs on any backend with >= 2
    devices in the mesh (CPU uses virtual devices; single-chip TPU has
    no exchange to compress and reports a skip marker)."""
    from distributed_embeddings_tpu.parallel.mesh import create_mesh

    devs = jax.devices()
    world = min(world, len(devs))
    record = {
        "metric": "wire_exchange_train_ab",
        "backend": devs[0].platform,
        "wire_vocab": vocab, "wire_width": width, "wire_tables": tables,
        "wire_batch": batch, "wire_hotness": hotness, "wire_world": world,
        "wire_optimizer": optimizer, "wire_iters": iters,
        "wire_format": wire,
        "git_sha": _git_sha(),
    }
    if world < 2:
        record["wire_error"] = (
            f"wire A/B needs a multi-device mesh, have {len(devs)} "
            "device(s) — no exchange collective exists at world 1")
        return record
    mesh = create_mesh(devs[:world])
    rng = np.random.RandomState(seed)
    # ONE copy of the tapped-model harness (tools/hlo_audit._build_model):
    # the A/B times exactly the program the byte audit lowers
    _ha = _load_hlo_audit()

    nb = 2
    data = [
        (np.zeros((batch, 1), np.float32),
         tuple(rng.randint(0, vocab, size=(batch, hotness)).astype(np.int32)
               for _ in range(tables)),
         rng.randn(batch).astype(np.float32))
        for _ in range(nb)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs),
                           *[(jnp.asarray(n), tuple(map(jnp.asarray, c)),
                              jnp.asarray(l)) for (n, c, l) in data])

    def time_arm(wire_fmt, key):
        model = _ha._build_model(vocab, width, "sum", tables=tables,
                                 mesh=mesh, exchange_wire=wire_fmt)
        emb = model.embedding
        params = {"embedding": emb.init(jax.random.PRNGKey(seed))}
        init_fn, step_fn = make_sparse_train_step(model, optimizer, lr=0.01)
        opt_state = init_fn(params)
        dt, warm, raw = _slope_time_scan(step_fn, params, opt_state,
                                         stacked, nb, iters)
        record[f"{key}_ms"] = round(dt * 1e3, 3)
        record[f"{key}_raw"] = raw
        return dt, warm, emb

    dt_f32, losses_f32, _ = time_arm("f32", "wire_f32")
    dt_c, losses_c, emb_c = time_arm(wire, "wire_compressed")
    reliable = dt_f32 > 1e-6 and dt_c > 1e-6
    record["wire_speedup"] = (round(dt_f32 / dt_c, 3) if reliable else 0.0)
    # parity marker: identical data + init, so the warm-up losses differ
    # only by the wire rounding — bounded, never zero for bf16
    n = min(len(losses_f32), len(losses_c))
    dev = float(np.max(np.abs(losses_f32[:n] - losses_c[:n])))
    scale = float(np.max(np.abs(losses_f32[:n]))) or 1.0
    record["wire_loss_max_dev"] = dev
    record["wire_loss_rel_dev"] = round(dev / scale, 6)
    rep = emb_c.exchange_padding_report(hotness=[hotness] * tables)
    record["wire_padding_report"] = {
        "act_bytes": rep["act_bytes"],
        "act_bytes_f32": rep["act_bytes_f32"],
        "act_wire_reduction": round(rep["act_wire_reduction"], 3),
        "exchanged_bytes": rep["exchanged_bytes"],
        "true_bytes": rep["true_bytes"],
        "wire_dtypes": {str(k): v for k, v in rep["wire_dtypes"].items()},
        "id_narrowed_groups": rep["id_narrowed_groups"],
    }
    # compiled-HLO byte audit of the same step shape (lowering-only, so
    # it is tunnel-safe and CI-checkable)
    try:
        arms = _ha.wire_byte_arms(
            vocab=min(vocab, 4096), width=width, tables=tables,
            batch=min(batch, 64), hotness=hotness,
            optimizer=optimizer, world=world)
        record["wire_hlo"] = arms
        comp = arms[1]
        record["wire_hlo_reduction"] = comp.get(
            "float_bytes_reduction_vs_f32")
    except Exception as e:  # noqa: BLE001 - audit must not kill the bench
        record["wire_hlo_error"] = str(e)[:200]
    return record


def wire_main(argv=None) -> int:
    """`bench.py --mode wire` entry point: one JSON line, like main()."""
    import argparse
    p = argparse.ArgumentParser(description="exchange wire-compression "
                                            "benchmark")
    p.add_argument("--mode", choices=["wire"], default="wire")
    p.add_argument("--vocab", type=int, default=100_000)
    p.add_argument("--width", type=int, default=128)
    p.add_argument("--tables", type=int, default=8)
    p.add_argument("--batch", type=int, default=8192)
    p.add_argument("--hotness", type=int, default=1)
    p.add_argument("--world", type=int, default=8)
    p.add_argument("--iters", type=int, default=5)
    p.add_argument("--optimizer", default="adagrad",
                   choices=["sgd", "adagrad", "adam"])
    p.add_argument("--wire", default="bf16", choices=["bf16", "bf16-sr"])
    p.add_argument("--seed", type=int, default=0)
    _add_profile_arg(p)
    args = p.parse_args(argv)
    if os.environ.get("DET_BENCH_FORCE_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    # the A/B needs a real mesh: request virtual CPU devices while the
    # backend is still uninitialized (hlo_audit._ensure_world — ONE copy
    # of the XLA_FLAGS dance; a real pod ignores it and uses its world)
    _load_hlo_audit()._ensure_world(max(2, args.world))
    try:
        record = _run_with_device_attribution(
            lambda: run_wire_bench(
                vocab=args.vocab, width=args.width, tables=args.tables,
                batch=args.batch, hotness=args.hotness, world=args.world,
                iters=args.iters, optimizer=args.optimizer,
                wire=args.wire, seed=args.seed),
            args.profile)
    except Exception as e:  # noqa: BLE001 - one JSON line, like main()
        import traceback
        traceback.print_exc()
        record = {"metric": "wire_exchange_train_ab",
                  "wire_error": str(e)[:300], "git_sha": _git_sha()}
    print(json.dumps(_stamp_metrics_snapshot(_stamp_audit_findings(record))))
    return 0 if "wire_error" not in record else 1


# ----------------------------------------------------------- storedtype
def run_storedtype_bench(vocab: int = 6000, width: int = 128,
                         tables: int = 8, batch: int = 256,
                         steps: int = 4, world: int = 8,
                         optimizer: str = "adagrad", seed: int = 0) -> dict:
    """Quantized row storage A/B (ISSUE 15): the SAME model trained and
    published at each storage/delta dtype, from shared weights and data.

    Three claims ride one record, per dtype arm:
      * capacity — measured stream payload bytes (snapshot + delta, read
        back from the written files) reconciled EXACTLY against the
        shared byte model (`ops/wire.delta_row_bytes` /
        `snapshot_row_bytes` — the same arithmetic
        `exchange_padding_report.delta_bytes_per_step` charges), plus
        the derived `delta_payload_reduction` / `snapshot_payload_
        reduction` vs the f32 arm (the >= 3.5x acceptance gate at
        width >= 128) and the quantized table's resident host bytes;
      * parity — publish->consume round trip: the consumer's merged
        weights against the publisher's (0.0 at f32 — the bit-exact
        contract; within the documented per-row quantization bound
        otherwise), and the trained-table deviation of the quantized
        arm against the f32 arm (the SR write-back convergence claim);
      * cost — steps/sec per arm (CPU: structural only; the projected
        TPU win is capacity/bandwidth, docs/perf_model.md "Quantized
        storage").
    """
    import tempfile
    import jax.numpy as jnp
    from distributed_embeddings_tpu.layers.dist_model_parallel import (
        DistributedEmbedding)
    from distributed_embeddings_tpu.layers.embedding import Embedding
    from distributed_embeddings_tpu.ops import wire as wire_ops
    from distributed_embeddings_tpu.parallel.mesh import create_mesh
    from distributed_embeddings_tpu.store import TableStore, scan_published

    from distributed_embeddings_tpu.ops import (
        sparse_update as sparse_update_ops)

    devs = jax.devices()
    if len(devs) < world:
        return {"skipped": f"need {world} devices, have {len(devs)}"}
    mesh = create_mesh(devs[:world])
    specs = [(vocab, width, "sum")] + [(64 + i, width, "sum")
                                       for i in range(tables - 1)]
    # Two residencies per dtype (ISSUE 17): the 'offload' arms put the
    # big bucket past the device budget (cold rows, host-exchange
    # decode + touched-rows host apply), the '_hbm' arms run with NO
    # budget so every bucket stays device-resident (decode at gather
    # inside the jitted step, master-weight-free row update). adam has
    # no master-weight-free rule — its quantized arms must offload
    # EVERYTHING (budget 1) and the HBM arms are skipped on record.
    hbm_ok = optimizer in sparse_update_ops.QUANTIZED_ROW_KINDS
    budget_off = (vocab * width) // 2 if hbm_ok else 1
    residencies = ([("", budget_off), ("_hbm", None)] if hbm_ok
                   else [("", budget_off)])

    class _M:
        def __init__(self, sd, budget):
            self.embedding = DistributedEmbedding(
                [Embedding(v, w, combiner=c) for v, w, c in specs],
                mesh=mesh, gpu_embedding_size=budget, storage_dtype=sd)

        def loss_fn(self, p, numerical, cats, labels, taps=None,
                    return_residuals=False):
            out = self.embedding(p["embedding"], list(cats), taps=taps,
                                 return_residuals=return_residuals)
            outs, res = out if return_residuals else (out, None)
            x = jnp.concatenate([o.reshape(o.shape[0], -1) for o in outs],
                                axis=1)
            loss = jnp.mean((jnp.sum(x, axis=1)
                             - labels.reshape(-1)) ** 2)
            return (loss, res) if return_residuals else loss

    rng = np.random.RandomState(seed)
    weights0 = [rng.randn(v, w).astype(np.float32) * 0.05
                for v, w, _ in specs]
    num = jnp.zeros((batch, 1), jnp.float32)
    data = [[jnp.asarray(rng.randint(0, v, size=(batch, 2))
                         .astype(np.int32)) for v, _, _ in specs]
            for _ in range(steps)]
    labels = jnp.asarray(rng.randn(batch).astype(np.float32))

    dtypes = ["f32", "int8"] + (["fp8"] if wire_ops.fp8_supported() else [])

    def resident_bytes(p):
        tot = sum(int(leaf.size) * leaf.dtype.itemsize
                  for leaf in p["tp"])
        for leaf in (p.get("tp_scale") or []):
            if leaf is not None:
                tot += int(leaf.size) * leaf.dtype.itemsize
        return tot

    arms, trained = {}, {}
    for suffix, budget in residencies:
        for sd in dtypes:
            name = sd + suffix
            model = _M(sd, budget)
            emb = model.embedding
            offl = [b for b in range(len(emb.plan.tp_buckets))
                    if emb.plan.tp_buckets[b].offload]
            if sd != "f32":
                # the lifted gate: every bucket quantizes, and the
                # residency split is exactly what the budget asked for
                assert emb.quantized_buckets == list(
                    range(len(emb.plan.tp_buckets))), \
                    "storedtype bench: eligibility drifted"
                assert (offl == [] if suffix == "_hbm"
                        else offl != []), \
                    "storedtype bench: residency drifted"
            init_fn, step_fn = make_sparse_train_step(
                model, optimizer, lr=0.05, donate=False)
            params = {"embedding": emb.set_weights(weights0)}
            state = init_fn(params)
            store = TableStore(emb, params["embedding"], delta_dtype=sd)
            pub_dir = tempfile.mkdtemp(prefix=f"storedtype_{name}_")
            snap_info = store.publish(pub_dir)          # the anchor
            t0 = time.perf_counter()
            for s in range(steps):
                store.observe(data[s])
                params, state, loss = step_fn(params, state, num,
                                              data[s], labels)
            jax.block_until_ready(params["embedding"]["tp"][0])
            dt = time.perf_counter() - t0
            store.commit(params["embedding"], state["emb"])
            delta_info = store.publish(pub_dir)
            # consume into a fresh replica and compare merged weights
            c_emb = _M(sd, budget).embedding
            consumer = TableStore(c_emb, c_emb.init(jax.random.PRNGKey(1)))
            for _, _, path in scan_published(pub_dir):
                consumer.apply_published(path)
            pub_w = emb.get_weights(params["embedding"])
            con_w = consumer.get_weights()
            parity = max(float(np.abs(a - b).max())
                         for a, b in zip(pub_w, con_w))
            trained[name] = pub_w
            arms[name] = {
                "storage_dtype": sd,
                "residency": ("device" if suffix == "_hbm" else "offload"),
                "snapshot_payload_bytes": snap_info["payload_bytes"],
                "snapshot_model_bytes": snap_info["model_payload_bytes"],
                "delta_payload_bytes": delta_info["payload_bytes"],
                "delta_model_bytes": delta_info["model_payload_bytes"],
                "snapshot_file_bytes": snap_info["bytes"],
                "delta_file_bytes": delta_info["bytes"],
                "delta_rows": delta_info["rows"],
                "bucket_resident_bytes": resident_bytes(
                    params["embedding"]),
                "quantized_rows_applied": emb.quantized_rows_applied_total,
                "quantized_apply_bytes": emb.quantized_apply_bytes_total,
                "payload_model_reconciled": (
                    snap_info["payload_bytes"] == snap_info[
                        "model_payload_bytes"]
                    and delta_info["payload_bytes"] == delta_info[
                        "model_payload_bytes"]),
                "publish_consume_parity_max_dev": parity,
                "steps_per_sec": round(steps / dt, 3),
            }
            if sd != "f32" and suffix == "":
                # touched-rows host apply accounting: layer totals must
                # reconcile EXACTLY through wire.delta_row_bytes
                a = arms[name]
                a["apply_bytes_reconciled"] = (
                    a["quantized_apply_bytes"]
                    == a["quantized_rows_applied"]
                    * wire_ops.delta_row_bytes(width, sd))
    f32 = arms["f32"]
    record = {
        "metric": "storedtype_stream_ab", "vocab": vocab, "width": width,
        "tables": tables, "batch": batch, "steps": steps, "world": world,
        "optimizer": optimizer, "arms": arms,
        "hbm_arms_skipped": (None if hbm_ok else
                             f"{optimizer} has no master-weight-free "
                             "quantized row-update rule"),
        "storedtype_parity_f32": f32["publish_consume_parity_max_dev"],
    }
    quant_arms = []
    for suffix, _ in residencies:
        base = arms["f32" + suffix]
        for sd in dtypes[1:]:
            name = sd + suffix
            quant_arms.append(name)
            a = arms[name]
            a["delta_payload_reduction"] = round(
                base["delta_payload_bytes"] / a["delta_payload_bytes"], 3)
            a["snapshot_payload_reduction"] = round(
                base["snapshot_payload_bytes"]
                / a["snapshot_payload_bytes"], 3)
            # vs the f32 twin at the SAME residency: for the _hbm arms
            # this is the ~4x rows-per-HBM-byte claim itself
            a["bucket_bytes_reduction"] = round(
                base["bucket_resident_bytes"]
                / a["bucket_resident_bytes"], 3)
            # trained-table deviation vs the f32 twin: the SR write-back
            # convergence claim at this shape (bounded, not bit-exact)
            a["train_table_max_dev_vs_f32"] = max(
                float(np.abs(x - y).max())
                for x, y in zip(trained["f32" + suffix], trained[name]))
    record["min_payload_reduction_required"] = 3.5
    record["over_bound"] = bool(
        any(arms["f32" + s]["publish_consume_parity_max_dev"] != 0.0
            for s, _ in residencies)
        or not all(a["payload_model_reconciled"] for a in arms.values())
        or not all(arms[n].get("apply_bytes_reconciled", True)
                   for n in quant_arms)
        or any(arms[n]["delta_payload_reduction"] < 3.5
               or arms[n]["snapshot_payload_reduction"] < 3.5
               or arms[n]["bucket_bytes_reduction"] < 3.5
               for n in quant_arms))
    return record


def storedtype_main(argv=None) -> int:
    """`bench.py --mode storedtype` entry point: one JSON line."""
    import argparse
    p = argparse.ArgumentParser(description="quantized row-storage "
                                            "stream/parity benchmark")
    p.add_argument("--mode", choices=["storedtype"], default="storedtype")
    p.add_argument("--vocab", type=int, default=6000)
    p.add_argument("--width", type=int, default=128)
    p.add_argument("--tables", type=int, default=8)
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--steps", type=int, default=4)
    p.add_argument("--world", type=int, default=8)
    p.add_argument("--optimizer", default="adagrad",
                   choices=["sgd", "adagrad", "adam"])
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    if os.environ.get("DET_BENCH_FORCE_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    _load_hlo_audit()._ensure_world(max(2, args.world))
    try:
        record = run_storedtype_bench(
            vocab=args.vocab, width=args.width, tables=args.tables,
            batch=args.batch, steps=args.steps, world=args.world,
            optimizer=args.optimizer, seed=args.seed)
    except Exception as e:  # noqa: BLE001 - one JSON line, like main()
        import traceback
        traceback.print_exc()
        record = {"metric": "storedtype_stream_ab",
                  "storedtype_error": str(e)[:300], "git_sha": _git_sha()}
    print(json.dumps(_stamp_metrics_snapshot(_stamp_audit_findings(record))))
    return 0 if not record.get("over_bound", False) \
        and "storedtype_error" not in record else 1


# ------------------------------------------------------------- lookahead
def run_lookahead_bench(vocab: int = 100_000, width: int = 64,
                        tables: int = 8, batch: int = 8192,
                        hotness: int = 2, world: int = 8, iters: int = 8,
                        optimizer: str = "adagrad", seed: int = 0,
                        parity_steps: int = 6,
                        patch_capacity: int = None,
                        stale_ok: bool = False) -> dict:
    """Lookahead pipeline A/B (ISSUE 9): the monolithic sparse train step
    vs the `schedule.LookaheadEngine` staged step over a `world`-device
    mesh, shared weights and data.

    Three claims ride one record:
      * parity — per-step losses of the engine at lookahead=1 against
        the monolithic step from the same init/data
        (`lookahead_loss_max_dev`; 0.0 = bit-exact, the acceptance gate
        when the touched-row patch is on), plus the engine's measured
        patch traffic (patched rows/step, overflow fallbacks) and
        per-stage compile counts (must be constant — no per-step
        re-specialization);
      * structure — the HLO overlap audit of the fused step embedded
        from tools/hlo_audit.py (`lookahead_overlap`): prefetch
        collectives dependency-free of the dense compute, zero extra
        sorts;
      * time — slope-timed step times for both arms. HONESTY NOTE: on
        CPU the engine arm is a host-driven loop (per-step dispatch +
        host patch bookkeeping) while the baseline runs as ONE scanned
        device program, so CPU wall-clock structurally UNDERSTATES the
        engine; `lookahead_speedup` is recorded but the claim is the
        overlap audit — the TPU number is decided by this mode at the
        next tunnel window (docs/perf_model.md "Lookahead prefetch").
    """
    from distributed_embeddings_tpu.parallel.mesh import create_mesh
    from distributed_embeddings_tpu.schedule import LookaheadEngine
    from distributed_embeddings_tpu.utils.profiling import fetch_sync
    from jax.sharding import NamedSharding, PartitionSpec

    devs = jax.devices()
    world = min(world, len(devs))
    record = {
        "metric": "lookahead_train_ab",
        "backend": devs[0].platform,
        "lookahead_vocab": vocab, "lookahead_width": width,
        "lookahead_tables": tables, "lookahead_batch": batch,
        "lookahead_hotness": hotness, "lookahead_world": world,
        "lookahead_optimizer": optimizer, "lookahead_iters": iters,
        "lookahead_stale_ok": bool(stale_ok),
        "git_sha": _git_sha(),
    }
    if world < 2:
        record["lookahead_error"] = (
            f"lookahead A/B needs a multi-device mesh, have {len(devs)} "
            "device(s) — no exchange collective exists at world 1")
        return record
    mesh = create_mesh(devs[:world])
    rng = np.random.RandomState(seed)
    _ha = _load_hlo_audit()

    def build_params(model):
        p = {"embedding": model.embedding.init(jax.random.PRNGKey(seed)),
             "head": jax.device_put(
                 _ha._head_params(tables, width, hotness, "sum"),
                 NamedSharding(mesh, PartitionSpec()))}
        return p

    nb = 2
    batches = []
    for _ in range(nb):
        num = jnp.zeros((batch, 1), jnp.float32)
        cats = [jnp.asarray(
            rng.randint(0, vocab, size=(batch, hotness)).astype(np.int32))
            for _ in range(tables)]
        lab = jnp.asarray(rng.randn(batch).astype(np.float32))
        batches.append((num, cats, lab))

    model = _ha._build_model(vocab, width, "sum", tables=tables,
                             mesh=mesh, dense_head=True)

    # ---- parity arm: same init/data, engine vs monolithic, per-step ----
    init_fn, step_fn = make_sparse_train_step(model, optimizer, lr=0.01)
    p0 = build_params(model)
    s0 = init_fn(p0)
    mono_losses = []
    p, s = p0, s0
    for i in range(parity_steps):
        num, cats, lab = batches[i % nb]
        p, s, loss = step_fn(p, s, num, list(cats), lab)
        mono_losses.append(float(loss))
    from distributed_embeddings_tpu.obs import default_registry
    engine = LookaheadEngine(model, optimizer, lr=0.01,
                             patch_capacity=patch_capacity,
                             stale_ok=stale_ok,
                             registry=default_registry())
    p2 = build_params(model)
    s2 = engine.init(p2)
    eng_losses = []
    for i in range(parity_steps):
        b = batches[i % nb]
        nxt = batches[(i + 1) % nb] if i + 1 < parity_steps else None
        p2, s2, loss = engine.step(p2, s2, b, nxt)
        eng_losses.append(float(loss))
    dev = float(np.max(np.abs(np.asarray(mono_losses)
                              - np.asarray(eng_losses))))
    record["lookahead_loss_max_dev"] = dev
    record["lookahead_parity_steps"] = parity_steps
    record["lookahead_engine_stats"] = dict(engine.stats)
    record["lookahead_compiles"] = engine.compile_counts()
    st = engine.stats
    # SAMPLES, not table rows: each patched sample re-exchanges its
    # hotness x tables row lookups — compare against the report's
    # prefetch_patch_rows_per_step only after that multiplication
    record["lookahead_patch_samples_per_step"] = (
        round(st["patched_samples"] / max(st["steps"], 1), 2))

    # ---- timing arms (shared fresh weights per arm) --------------------
    # each arm runs inside a bench span (ISSUE 14): under --profile the
    # engine arm's window is where the exposed-exchange fraction — the
    # lookahead projection's headline metric — is measured from the
    # device timeline (collective op time not covered by dense compute)
    from distributed_embeddings_tpu.obs import span
    stacked = jax.tree.map(
        lambda *xs: jnp.stack(xs),
        *[(n, tuple(c), l) for (n, c, l) in batches])
    pt = build_params(model)
    dt_base, _, raw_base = _slope_time_scan(
        step_fn, pt, init_fn(pt), stacked, nb, iters,
        span_path="bench/lookahead/base")
    record["lookahead_base_ms"] = round(dt_base * 1e3, 3)
    record["lookahead_base_raw"] = raw_base

    eng_t = LookaheadEngine(model, optimizer, lr=0.01,
                            patch_capacity=patch_capacity,
                            stale_ok=stale_ok,
                            registry=default_registry())
    pe = build_params(model)
    se = eng_t.init(pe)

    # the batch cycle must be CONTINUOUS across run_n calls: a restart
    # at 0 would mismatch the staged carry's tag at the t1/t2 boundary
    # and put a cold-fill prefetch inside the timed window
    step_idx = {"i": 0}

    def run_n(p, s, n):
        loss = None
        for _ in range(n):
            i = step_idx["i"]
            b = batches[i % nb]
            p, s, loss = eng_t.step(p, s, b, batches[(i + 1) % nb])
            step_idx["i"] = i + 1
        return p, s, loss

    pe, se, loss = run_n(pe, se, 2)          # compile + pipeline fill
    fetch_sync(loss)
    # span around ONLY the timed steady-state region (compile and
    # pipeline fill excluded — same rule as _slope_time_scan): this
    # window's collective exposure IS the measured E of the projection
    with span("bench/lookahead/engine", default_registry()):
        t0 = time.perf_counter()
        pe, se, loss = run_n(pe, se, iters)
        fetch_sync(loss)
        t1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        pe, se, loss = run_n(pe, se, 2 * iters)
        fetch_sync(loss)
        t2 = time.perf_counter() - t0
    dt_eng = max(t2 - t1, 1e-9) / iters
    record["lookahead_ms"] = round(dt_eng * 1e3, 3)
    record["lookahead_raw"] = {"t1_ms": round(t1 * 1e3, 3),
                               "t2_ms": round(t2 * 1e3, 3),
                               "iters": iters}
    reliable = dt_base > 1e-6 and dt_eng > 1e-6
    record["lookahead_speedup"] = (round(dt_base / dt_eng, 3)
                                   if reliable else 0.0)
    record["lookahead_cpu_note"] = (
        "CPU wall-clock structurally understates the engine (host-driven "
        "loop vs one scanned baseline program); the overlap audit is the "
        "claim, the TPU number lands at the next tunnel window")

    # ---- static accounting + HLO overlap audit -------------------------
    rep = model.embedding.exchange_padding_report(
        hotness=[hotness] * tables, batch=batch, lookahead=1)
    record["lookahead_padding_report"] = {
        "prefetch_patch_rows_per_step": rep["prefetch_patch_rows_per_step"],
        "prefetch_patch_bytes_per_step":
            rep["prefetch_patch_bytes_per_step"],
        "touched_rows_per_step": rep["touched_rows_per_step"],
        "act_bytes": rep["act_bytes"],
    }
    try:
        ov = _ha.audit_lookahead_overlap(
            vocab=min(vocab, 4096), width=width, tables=tables,
            batch=min(batch, 64), hotness=hotness, optimizer=optimizer,
            world=world, stale_ok=stale_ok)
        record["lookahead_overlap"] = ov
        record["lookahead_overlap_candidates"] = ov.get(
            "fused_overlap_candidates")
        record["lookahead_extra_sorts"] = ov.get("extra_sorts")
    except Exception as e:  # noqa: BLE001 - audit must not kill the bench
        record["lookahead_overlap_error"] = str(e)[:200]
    return record


def lookahead_main(argv=None) -> int:
    """`bench.py --mode lookahead` entry point: one JSON line."""
    import argparse
    p = argparse.ArgumentParser(description="lookahead pipeline benchmark")
    p.add_argument("--mode", choices=["lookahead"], default="lookahead")
    p.add_argument("--vocab", type=int, default=100_000)
    p.add_argument("--width", type=int, default=64)
    p.add_argument("--tables", type=int, default=8)
    p.add_argument("--batch", type=int, default=8192)
    p.add_argument("--hotness", type=int, default=2)
    p.add_argument("--world", type=int, default=8)
    p.add_argument("--iters", type=int, default=8)
    p.add_argument("--parity_steps", type=int, default=6)
    p.add_argument("--patch_capacity", type=int, default=None)
    p.add_argument("--stale_ok", action="store_true")
    p.add_argument("--optimizer", default="adagrad",
                   choices=["sgd", "adagrad", "adam"])
    p.add_argument("--seed", type=int, default=0)
    _add_profile_arg(p)
    args = p.parse_args(argv)
    if os.environ.get("DET_BENCH_FORCE_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    _load_hlo_audit()._ensure_world(max(2, args.world))
    try:
        record = _run_with_device_attribution(
            lambda: run_lookahead_bench(
                vocab=args.vocab, width=args.width, tables=args.tables,
                batch=args.batch, hotness=args.hotness, world=args.world,
                iters=args.iters, optimizer=args.optimizer,
                seed=args.seed, parity_steps=args.parity_steps,
                patch_capacity=args.patch_capacity,
                stale_ok=args.stale_ok),
            args.profile)
        att = record.get("device_attribution")
        if isinstance(att, dict) and "error" not in att:
            # the headline projection input (docs/perf_model.md
            # "Lookahead prefetch"): E = exposed exchange fraction,
            # measured from the ENGINE arm's device timeline ONLY — no
            # whole-run fallback: the global fraction includes the
            # non-overlapped base arm (fully exposed by construction)
            # and would silently overstate E exactly when async
            # dispatch pushed the engine's ops out of their window
            eng = att["collective"]["per_span"].get(
                "bench/lookahead/engine")
            record["lookahead_measured_exposed_exchange_fraction"] = (
                eng["exposed_fraction"] if eng else None)
            if eng is None:
                record["lookahead_exposed_exchange_note"] = (
                    "no collective device ops attributed inside the "
                    "engine-arm span (async-dispatch tail?) — E "
                    "unmeasured this run, NOT substituted")
    except Exception as e:  # noqa: BLE001 - one JSON line, like main()
        import traceback
        traceback.print_exc()
        record = {"metric": "lookahead_train_ab",
                  "lookahead_error": str(e)[:300], "git_sha": _git_sha()}
    print(json.dumps(_stamp_metrics_snapshot(_stamp_audit_findings(record))))
    return 0 if "lookahead_error" not in record else 1


# ---------------------------------------------------------------- ingest
def _write_ingest_files(tmpdir: str, distinct: int, batch: int,
                        features: int, numerical: int, alpha: float,
                        seed: int) -> dict:
    """Materialize a split-binary-like synthetic dataset on disk: raw int64
    power-law keys (feature-major per batch, so per-feature reads are
    contiguous like cat_i.bin), f16 numericals, bool labels. The read stage
    preads real bytes; cycling `distinct` batches keeps the file small and
    the page cache warm (steady-state regime — the vocab is fully built
    after the first cycle, exactly the duplicate-heavy regime docs/parity.md
    measures the hash at)."""
    rng = np.random.RandomState(seed)
    sizes = {"keys": features * batch * 8, "numerical": numerical * batch * 2,
             "label": batch}
    paths = {k: os.path.join(tmpdir, f"{k}.bin") for k in sizes}
    files = {k: open(p, "wb") for k, p in paths.items()}
    try:
        for _ in range(distinct):
            keys = (rng.zipf(alpha, size=(features, batch)) * 2654435761
                    % (1 << 40)).astype(np.int64)
            files["keys"].write(keys.tobytes())
            files["numerical"].write(
                rng.rand(batch, numerical).astype(np.float16).tobytes())
            files["label"].write(
                rng.randint(0, 2, batch).astype(np.bool_).tobytes())
    finally:
        for f in files.values():
            f.close()
    return {"paths": paths, "sizes": sizes}


def make_ingest_step(lr: float = 0.05):
    """The consumer: a jitted sparse-update train step stand-in — gather
    [B, F] rows, sum-combine, logistic head, manual backward with a
    row-wise scatter-add table update (the embedding-bound shape of the
    real sparse path; device cost scales with batch x features x dim like
    training does). Donated table/head buffers update in place."""
    import functools

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(table, w, numerical, idx, labels):
        rows = table[idx]                          # [B, F, D] gather
        h = rows.sum(axis=1)                       # [B, D] sum combiner
        k = min(h.shape[1], numerical.shape[1])    # static inside jit
        h = h.at[:, :k].add(numerical[:, :k])
        logits = h @ w                             # [B]
        y = labels[:, 0]
        loss = jnp.mean(jnp.maximum(logits, 0) - logits * y
                        + jnp.log1p(jnp.exp(-jnp.abs(logits))))
        dlogits = (jax.nn.sigmoid(logits) - y) / logits.shape[0]
        dw = h.T @ dlogits                         # [D]
        dh = dlogits[:, None] * w[None, :]         # [B, D]
        drows = jnp.broadcast_to(dh[:, None, :], rows.shape)
        table = table.at[idx].add(-lr * drows)     # sparse row update
        return table, w - lr * dw, loss

    return step


def run_ingest_bench(batches: int = 32, batch: int = 16384,
                     features: int = 26, numerical: int = 13,
                     dim: int = 16, max_tokens: int = 1 << 19,
                     alpha: float = 1.2, distinct: int = 8,
                     depth: int = 2, seed: int = 0, reps: int = 3) -> dict:
    """Ingestion benchmark: serial vs pipelined end-to-end samples/s.

    The end-to-end loop is read (pread) -> preprocess (IntegerLookup hash +
    min-dtype cast + feature split, one fused pass) -> stage (device_put) ->
    consume (jitted sparse-update step, loss fetched per batch — the CPU
    `fit` lockstep semantics). The serial arm runs every stage in the
    consumer thread (the seed's behavior); the pipelined arm runs the three
    host stages in persistent background workers (utils.pipeline) so they
    hide under the device step. Per-stage wall times ride in the record;
    the pipelined rate should approach the slowest single-stage bound
    instead of the sum of stages. Runs on any backend incl. CPU (the
    tier-1 smoke path) — the whole optimisation is host-side.
    """
    import tempfile
    import shutil
    from distributed_embeddings_tpu.layers.embedding import IntegerLookup
    from distributed_embeddings_tpu.utils.metrics import LatencyHistogram
    from distributed_embeddings_tpu.utils.pipeline import (IngestPipeline,
                                                           SerialPipeline)

    tmpdir = tempfile.mkdtemp(prefix="det_ingest_")
    try:
        layout = _write_ingest_files(tmpdir, distinct, batch, features,
                                     numerical, alpha, seed)
        paths, sizes = layout["paths"], layout["sizes"]
        fds = {k: os.open(p, os.O_RDONLY) for k, p in paths.items()}
        try:
            lookups = [IntegerLookup(max_tokens) for _ in range(features)]

            def read(i):
                d = i % distinct
                return {k: os.pread(fds[k], sizes[k], d * sizes[k])
                        for k in fds}

            def preprocess(bufs):
                # one fused pass over the raw batch: hash translate per
                # feature (contiguous slices), min-dtype cast, feature
                # stack, f16 -> f32 numericals, label reshape
                keys = np.frombuffer(bufs["keys"], np.int64).reshape(
                    features, batch)
                idx = np.empty((batch, features), np.int32)
                for f in range(features):
                    idx[:, f] = lookups[f](keys[f])
                num = np.frombuffer(bufs["numerical"], np.float16).reshape(
                    batch, numerical).astype(np.float32)
                labels = np.frombuffer(
                    bufs["label"], np.bool_).astype(np.float32)[:, None]
                return num, idx, labels

            def stage(b):
                return jax.device_put(b)

            step = make_ingest_step()
            rng = np.random.RandomState(seed + 1)
            table0 = rng.rand(max_tokens + 1, dim).astype(np.float32) * 0.01
            w0 = rng.rand(dim).astype(np.float32) * 0.01

            def consume_loop(it, consume_hist):
                """Drive the consumer over `it`; fetch-sync the loss each
                batch (block_until_ready lies on some backends; a host
                fetch cannot)."""
                table = jax.device_put(table0.copy())
                w = jax.device_put(w0.copy())
                n, lv = 0, 0.0
                for num, idx, labels in it:
                    t0 = time.perf_counter()
                    table, w, loss = step(table, w, num, idx, labels)
                    lv = float(loss)
                    consume_hist.record(time.perf_counter() - t0)
                    n += 1
                if not np.isfinite(lv):
                    raise RuntimeError(f"non-finite ingest loss: {lv}")
                return n

            stages = [("preprocess", preprocess), ("stage", stage)]

            def src(n):
                return (read(i) for i in range(n))

            # warmup OFF the clock: one full cycle builds every vocab
            # (after it, the key stream is all-hits — steady state), plus
            # the step compile and the page cache
            consume_loop(SerialPipeline(src(distinct), stages),
                         LatencyHistogram())

            # interleaved arms x reps, best-of-reps per arm: the shared-vCPU
            # host shows multi-second steal windows (same mitigation class
            # as run_at_batch's slope timing) — a single paired run can
            # charge a steal burst to either arm; the best rep per arm is
            # the contention-free estimate and every rep rides along in
            # ingest_raw for honesty
            arms = (("serial",
                     lambda: SerialPipeline(src(batches), stages)),
                    ("pipelined",
                     lambda: IngestPipeline(src(batches), stages,
                                            depth=depth)))
            results = {}
            raw = []
            # per-arm per-stage histograms MERGED across reps
            # (LatencyHistogram.merge): the aggregate distribution, not
            # just whichever rep happened to run last
            agg_hists: dict = {}
            for rep in range(max(1, reps)):
                for label, make_pipe in arms:
                    pipe = make_pipe()
                    consume_hist = LatencyHistogram()
                    t0 = time.perf_counter()
                    n = consume_loop(pipe, consume_hist)
                    dt = max(time.perf_counter() - t0, 1e-9)
                    pipe.close()
                    stage_ms = {name: s["mean_ms"] for name, s
                                in pipe.stage_summaries().items()}
                    stage_ms["consume"] = consume_hist.summary()["mean_ms"]
                    rep_hists = dict(pipe.stage_histograms())
                    rep_hists["consume"] = consume_hist
                    tgt = agg_hists.setdefault(label, {})
                    for name, h in rep_hists.items():
                        if name in tgt:
                            tgt[name].merge(h)
                        else:
                            tgt[name] = h
                    res = {"samples_per_sec": round(n * batch / dt),
                           "wall_s": round(dt, 3), "stage_ms": stage_ms}
                    raw.append({"rep": rep, "arm": label, **res})
                    if (label not in results or res["samples_per_sec"]
                            > results[label]["samples_per_sec"]):
                        results[label] = res

            # all-reps aggregates onto the process-default registry so
            # the record's metrics_snapshot (ISSUE 11) carries the same
            # distributions as ingest_stage_summary_all_reps — the
            # per-rep pipelines keep their private per-instance
            # registries (the A/B arms must not share instruments)
            from distributed_embeddings_tpu.obs import default_registry
            obs_reg = default_registry()
            for arm_label, hs in agg_hists.items():
                for sname, h in hs.items():
                    obs_reg.histogram("ingest/stage_seconds_all_reps",
                                      arm=arm_label, stage=sname).merge(h)

            ser = results["serial"]["samples_per_sec"]
            pip = results["pipelined"]["samples_per_sec"]
            pip_stage_ms = results["pipelined"]["stage_ms"]
            bottleneck = max(pip_stage_ms, key=pip_stage_ms.get)
            bound = round(batch / (pip_stage_ms[bottleneck] / 1e3)) \
                if pip_stage_ms[bottleneck] else 0
            return {
                "metric": "ingest_serial_vs_pipelined_powerlaw",
                "backend": jax.devices()[0].platform,
                "ingest_batch": batch,
                "ingest_batches": batches,
                "ingest_features": features,
                "ingest_numerical": numerical,
                "ingest_dim": dim,
                "ingest_max_tokens": max_tokens,
                "ingest_zipf_alpha": alpha,
                "ingest_depth": depth,
                "ingest_serial_samples_per_sec": ser,
                "ingest_pipelined_samples_per_sec": pip,
                "ingest_speedup": round(pip / ser, 3) if ser else 0.0,
                "ingest_serial_stage_ms": results["serial"]["stage_ms"],
                "ingest_pipelined_stage_ms": pip_stage_ms,
                "ingest_bottleneck_stage": bottleneck,
                "ingest_stage_bound_samples_per_sec": bound,
                "ingest_vs_stage_bound": round(pip / bound, 3) if bound
                else 0.0,
                "ingest_reps": max(1, reps),
                "ingest_raw": raw,
                # all-reps aggregate per-stage distributions (merged
                # histograms; the headline stage_ms fields above remain
                # the best-rep contention-free estimate)
                "ingest_stage_summary_all_reps": {
                    arm: {name: h.summary() for name, h in hs.items()}
                    for arm, hs in agg_hists.items()},
                "ingest_vocab_built": int(sum(lk.size for lk in lookups)),
                "git_sha": _git_sha(),
            }
        finally:
            for fd in fds.values():
                os.close(fd)
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)


def ingest_main(argv=None) -> int:
    """`bench.py --mode ingest` entry point: one JSON line, like main()."""
    import argparse
    p = argparse.ArgumentParser(description="ingestion pipeline benchmark")
    p.add_argument("--mode", choices=["ingest"], default="ingest")
    p.add_argument("--batches", type=int, default=32)
    p.add_argument("--batch", type=int, default=16384)
    p.add_argument("--features", type=int, default=26)
    p.add_argument("--numerical", type=int, default=13)
    p.add_argument("--dim", type=int, default=16)
    p.add_argument("--max_tokens", type=int, default=1 << 19)
    p.add_argument("--alpha", type=float, default=1.2)
    p.add_argument("--distinct", type=int, default=8)
    p.add_argument("--depth", type=int, default=2)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--reps", type=int, default=3,
                   help="interleaved serial/pipelined repetitions; the "
                        "headline takes each arm's best rep (steal-window "
                        "robust), all reps ride in ingest_raw")
    _add_profile_arg(p)
    args = p.parse_args(argv)
    if os.environ.get("DET_BENCH_FORCE_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    try:
        record = _run_with_device_attribution(
            lambda: run_ingest_bench(
                batches=args.batches, batch=args.batch,
                features=args.features, numerical=args.numerical,
                dim=args.dim, max_tokens=args.max_tokens,
                alpha=args.alpha, distinct=args.distinct,
                depth=args.depth, seed=args.seed, reps=args.reps),
            args.profile)
    except Exception as e:  # noqa: BLE001 - one JSON line, like main()
        import traceback
        traceback.print_exc()
        record = {"metric": "ingest_serial_vs_pipelined_powerlaw",
                  "ingest_error": str(e)[:300], "git_sha": _git_sha()}
    print(json.dumps(_stamp_metrics_snapshot(_stamp_audit_findings(record))))
    return 0 if "ingest_error" not in record else 1


# --------------------------------------------------------------- kernels
def run_kernels_bench(vocab: int = 65536, width: int = 32,
                      batch: int = 4096, hotness: int = 4, iters: int = 5,
                      optimizer: str = "adagrad", parity_steps: int = 3,
                      seed: int = 0) -> dict:
    """Fused-sparse-path kernel A/B (ISSUE 12): xla vs tiled vs pallas
    arms for the fused forward (DET_LOOKUP_PATH) and the fused
    backward+optimizer (DET_SCATTER_IMPL strategy), single chip, shared
    weights/data, slope-timed via `_slope_time_scan`.

    Three claims per record:
      * parity — per-step losses of each update arm against the 'sort'
        strategy from the same init/data (`kernels_parity_*`; the pallas
        arm's marker must be 0.0 — the bit-exactness gate — while the
        tiled arm documents its f32-tolerance contract) and the forward
        arms' max output deviation vs the XLA gather+einsum;
      * time — slope-timed forward-only and full-step times per arm.
        HONESTY NOTE: on CPU every Pallas arm runs the kernels in
        INTERPRET mode — a structural understatement of orders of
        magnitude (the grid executes as emulated XLA ops, nothing runs
        on an MXU) — so CPU arm times are schema/parity evidence ONLY;
        the record says so (`kernels_cpu_note`) and the TPU decision is
        deferred to the tunnel queue (ROADMAP standing item);
      * projection — the perf_model.md reference-shape predictions the
        next tunnel window must settle (`kernels_tpu_projections`),
        stamped verbatim so the falsifiable numbers ride with the arms
        that will measure them.
    """
    from distributed_embeddings_tpu.utils.profiling import fetch_sync
    devs = jax.devices()
    record = {
        "metric": "kernels_fused_ab", "backend": devs[0].platform,
        "kernels_vocab": vocab, "kernels_width": width,
        "kernels_batch": batch, "kernels_hotness": hotness,
        "kernels_iters": iters, "kernels_optimizer": optimizer,
        "git_sha": _git_sha(),
        "kernels_cpu_note": (
            "CPU arms run the Pallas kernels in INTERPRET mode — a "
            "structural understatement (emulated grid, no MXU); CPU "
            "times are schema/parity evidence only, the step-time claim "
            "is decided by this mode at the next tunnel window"),
        # docs/perf_model.md 'Fused sparse path' — the falsifiable
        # per-arm TPU predictions this mode settles on hardware
        "kernels_tpu_projections": {
            "dlrm_fused_fwd_ms": 5.0,
            "dlrm_fused_bwd_opt_ms": 7.5,
            "dlrm_step_ms": 25.0, "dlrm_step_ms_measured_xla": 169.0,
            "tiny_fused_fwd_ms": 30.0, "tiny_fused_fwd_ms_measured": 120.0,
            "tiny_fused_bwd_opt_ms": 58.0,
            "tiny_bwd_opt_ms_measured_xla_sort": 1228.0,
        },
    }
    _ha = _load_hlo_audit()
    rng = np.random.RandomState(seed)
    nb = 2
    raw_batches = []
    for _ in range(nb):
        cats = [jnp.asarray(rng.randint(0, vocab, size=(batch, hotness))
                            .astype(np.int32))]
        lab = jnp.asarray(rng.randn(batch).astype(np.float32))
        raw_batches.append((jnp.zeros((batch, 1), jnp.float32), cats, lab))
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs),
                           *[(n, tuple(c), l) for (n, c, l) in raw_batches])
    key = jax.random.PRNGKey(seed)

    def build_model():
        m = _ha._build_model(vocab, width, "sum", tables=1, mesh=None,
                             dense_head=True)
        m._head_width = _ha._head_params(1, width, hotness, "sum")
        return m

    # ---- forward arms: xla gather+einsum vs tiled vs fused ------------
    # the parity reference is pinned to the XLA arm: if it failed, the
    # deviation keys are omitted rather than silently rebased onto
    # whichever arm happened to succeed first. Each arm runs inside a
    # bench span (ISSUE 14): under --profile the span's TraceAnnotation
    # is the attribution window that splits device time per arm.
    from distributed_embeddings_tpu.obs import default_registry, span
    fwd_ref = None
    for arm, env in (("xla", {"DET_LOOKUP_PATH": "xla"}),
                     ("tiled", {"DET_LOOKUP_PATH": "tiled"}),
                     ("fused", {"DET_LOOKUP_PATH": "fused"})):
        for k, v in env.items():
            os.environ[k] = v
        try:
            model = build_model()
            emb = model.embedding
            params = {"embedding": emb.init(key)}
            cats0 = raw_batches[0][1]
            fwd = jax.jit(lambda p, c, e=emb: e.apply(p["embedding"],
                                                      list(c)))
            out = fwd(params, cats0)
            fetch_sync(out)
            # the span opens around ONLY the timed replays: a window
            # that swallowed init/compile device ops would inflate the
            # per-arm attribution the runbook settles projections with
            with span(f"bench/kernels/fwd/{arm}", default_registry()):
                t0 = time.perf_counter()
                fetch_sync(fwd(params, cats0))
                t1 = time.perf_counter() - t0
                t0 = time.perf_counter()
                fetch_sync(fwd(params, cats0))
                fetch_sync(fwd(params, cats0))
                t2 = time.perf_counter() - t0
            record[f"kernels_fwd_{arm}_ms"] = round(
                max(t2 - t1, 1e-9) * 1e3, 3)
            o = np.asarray(jax.device_get(out[0]))
            if arm == "xla":
                fwd_ref = o
            elif fwd_ref is not None:
                record[f"kernels_fwd_{arm}_max_dev"] = float(
                    np.max(np.abs(o - fwd_ref)))
        except Exception as e:  # noqa: BLE001 - an arm must not kill it
            record[f"kernels_fwd_{arm}_error"] = str(e)[:200]
        finally:
            for k in env:
                os.environ.pop(k, None)

    # ---- update arms: full sparse step, strategy A/B ------------------
    parity_losses = {}
    for arm in ("sort", "tiled", "pallas"):
        try:
            model = build_model()
            init_fn, step_fn = make_sparse_train_step(
                model, optimizer, lr=0.05, strategy=arm)
            params = {"embedding": model.embedding.init(key),
                      "head": model._head_width}
            state = init_fn(params)
            losses = []
            p, s = params, state
            for i in range(parity_steps):
                num, cats, lab = raw_batches[i % nb]
                p, s, loss = step_fn(p, s, num, list(cats), lab)
                losses.append(float(loss))
            parity_losses[arm] = losses
            model = build_model()
            init_fn, step_fn = make_sparse_train_step(
                model, optimizer, lr=0.05, strategy=arm)
            params = {"embedding": model.embedding.init(key),
                      "head": model._head_width}
            dt, _, raw = _slope_time_scan(
                step_fn, params, init_fn(params), stacked, nb, iters,
                span_path=f"bench/kernels/step/{arm}")
            record[f"kernels_step_{arm}_ms"] = round(dt * 1e3, 3)
            record[f"kernels_step_{arm}_raw"] = raw
        except Exception as e:  # noqa: BLE001
            record[f"kernels_step_{arm}_error"] = str(e)[:300]
    if "sort" in parity_losses:
        base = np.asarray(parity_losses["sort"])
        for arm in ("tiled", "pallas"):
            if arm in parity_losses:
                record[f"kernels_parity_max_dev_{arm}"] = float(
                    np.max(np.abs(np.asarray(parity_losses[arm]) - base)))
        record["kernels_parity_steps"] = parity_steps
        # the bit-exactness gate: the fused strategy must REPRODUCE the
        # sort strategy's losses, not approximate them
        record["kernels_pallas_bitexact"] = (
            record.get("kernels_parity_max_dev_pallas") == 0.0)
    # sort-count fingerprint of the arms being timed (lowering only)
    try:
        record["kernels_hlo_sort_audit"] = [
            _ha.audit_tapped_step(vocab=vocab, width=width,
                                  optimizer=optimizer, strategy="pallas"),
            _ha.audit_tapped_step(vocab=vocab, width=width,
                                  optimizer=optimizer, strategy="pallas",
                                  lookup_path="fused"),
        ]
    except Exception as e:  # noqa: BLE001
        record["kernels_hlo_sort_audit_error"] = str(e)[:200]
    return record


def kernels_main(argv=None) -> int:
    """`bench.py --mode kernels` entry point: one JSON line."""
    import argparse
    p = argparse.ArgumentParser(description="fused sparse-path kernel A/B")
    p.add_argument("--mode", choices=["kernels"], default="kernels")
    p.add_argument("--vocab", type=int, default=65536)
    p.add_argument("--width", type=int, default=32)
    p.add_argument("--batch", type=int, default=4096)
    p.add_argument("--hotness", type=int, default=4)
    p.add_argument("--iters", type=int, default=5)
    p.add_argument("--parity_steps", type=int, default=3)
    p.add_argument("--optimizer", default="adagrad",
                   choices=["sgd", "adagrad", "adam"])
    p.add_argument("--seed", type=int, default=0)
    _add_profile_arg(p)
    args = p.parse_args(argv)
    if os.environ.get("DET_BENCH_FORCE_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
        # virtual world so the per-record audit stamp can lower the
        # meshed program matrix (the kernel arms themselves are 1-chip)
        _load_hlo_audit()._ensure_world(8)
    _isolate_from_measured_defaults()
    try:
        record = _run_with_device_attribution(
            lambda: run_kernels_bench(
                vocab=args.vocab, width=args.width, batch=args.batch,
                hotness=args.hotness, iters=args.iters,
                optimizer=args.optimizer, parity_steps=args.parity_steps,
                seed=args.seed),
            args.profile)
        if args.profile:
            _kernels_reconcile(record, iters=args.iters)
    except Exception as e:  # noqa: BLE001 - one JSON line, like main()
        import traceback
        traceback.print_exc()
        record = {"metric": "kernels_fused_ab",
                  "kernels_error": str(e)[:300], "git_sha": _git_sha()}
    print(json.dumps(_stamp_metrics_snapshot(_stamp_audit_findings(record))))
    return 0 if "kernels_error" not in record else 1


# ------------------------------------------------------------- soak mode
# (ISSUE 13 / ROADMAP item 5) The composed production soak: the ingest
# pipeline feeds fit(lookahead=, vocab=, store=) publishing row deltas
# while a fleet of InferenceEngine replicas consumes them mid-query —
# under scripted adversarial scenarios (tools/soak_scenarios/*.json:
# zipf drift, flash crowds, late-join re-anchor, publisher pause, and
# deterministic fault plans from distributed_embeddings_tpu/faults/)
# with SLO accounting through the obs registry (tools/slo_soak.json).

SOAK_SCENARIO_DEFAULTS = {
    "steps": 16, "batch": 192, "tables": 2, "vocab": 1500, "width": 8,
    "hotness": 2, "world": 8, "optimizer": "adagrad", "lr": 0.05,
    "alpha": 1.2, "seed": 0,
    "publish_every": 2, "snapshot_every": 3, "lookahead": 1,
    "vocab_manage": None,
    "replicas": 2, "requests_per_round": 2, "request_batch": 16,
    "poll_every_rounds": 1, "late_join": None,
    "traffic": None, "fault_plan": None,
    "churn": None, "fleet": None,
    "knobs": None,
}

_SOAK_VOCAB_DEFAULTS = {"slack": 192, "admit_threshold": 1,
                        "decay": 0.97, "every": 4, "key_space": 4000}

# fleet-tier scenario knobs (ISSUE 16, bench.py --mode fleet); a soak
# scenario's optional "fleet" dict overrides these
_FLEET_DEFAULTS = {
    "cache_capacity": 192, "canaries": 1, "max_queue_depth": 64,
    "max_queue_rows": None, "vnodes": 32, "fleet_sizes": [1, 2, 4],
    "keys": 32, "locality": 0.9, "user_window": 32,
    "sweep_requests": 96,
}


def load_soak_scenario(path_or_doc) -> dict:
    """Load + validate one soak scenario (a JSON file path or a dict).
    Scenarios are DATA, not code (ROADMAP item 5): unknown keys refuse,
    the fault plan's specs are constructed (so a scenario naming an
    impossible (point, kind) pair fails at load, not mid-soak), and the
    lookahead x vocab-maintenance composition refusal is checked here
    with the same rule `training.fit` enforces."""
    if isinstance(path_or_doc, str):
        with open(path_or_doc) as f:
            doc = json.load(f)
    else:
        doc = dict(path_or_doc)
    if "name" not in doc:
        raise ValueError("soak scenario needs a 'name'")
    unknown = set(doc) - set(SOAK_SCENARIO_DEFAULTS) - {"name",
                                                        "description"}
    if unknown:
        raise ValueError(f"soak scenario {doc['name']!r}: unknown keys "
                         f"{sorted(unknown)}")
    sc = {**SOAK_SCENARIO_DEFAULTS, **doc}
    for k in ("steps", "batch", "tables", "vocab", "width", "hotness",
              "replicas", "publish_every", "request_batch"):
        if int(sc[k]) <= 0:
            raise ValueError(f"soak scenario {sc['name']!r}: {k} must "
                             f"be positive, got {sc[k]}")
    if sc["vocab_manage"] is not None:
        vm = {**_SOAK_VOCAB_DEFAULTS, **sc["vocab_manage"]}
        sc["vocab_manage"] = vm
        if sc["lookahead"] and vm["every"]:
            raise ValueError(
                f"soak scenario {sc['name']!r}: lookahead>0 composes "
                "only with translate-only vocab (vocab_manage.every == "
                "0) — the same refusal training.fit enforces")
    if sc["late_join"] is not None:
        lj = {"replica": int(sc["replicas"]) - 1, "at_frac": 0.5,
              **sc["late_join"]}
        if not 1 <= int(lj["replica"]) < int(sc["replicas"]):
            raise ValueError(
                f"soak scenario {sc['name']!r}: late_join.replica must "
                "be in [1, replicas) — replica 0 serves from the start")
        sc["late_join"] = lj
    if sc["churn"] is not None:
        evs = []
        for ev in sc["churn"]:
            e = {"at_frac": 0.5, **ev}
            if e.get("action") not in ("join", "leave"):
                raise ValueError(
                    f"soak scenario {sc['name']!r}: churn action must be "
                    f"'join' or 'leave', got {e.get('action')!r}")
            if "replica" not in e or int(e["replica"]) < 0:
                raise ValueError(
                    f"soak scenario {sc['name']!r}: churn events need a "
                    "non-negative 'replica' index")
            if not 0.0 <= float(e["at_frac"]) <= 1.0:
                raise ValueError(
                    f"soak scenario {sc['name']!r}: churn at_frac must "
                    f"be in [0, 1], got {e['at_frac']}")
            evs.append(e)
        sc["churn"] = sorted(evs, key=lambda e: float(e["at_frac"]))
    if sc["fleet"] is not None:
        fl = {**_FLEET_DEFAULTS, **sc["fleet"]}
        unknown = set(fl) - set(_FLEET_DEFAULTS)
        if unknown:
            raise ValueError(f"soak scenario {sc['name']!r}: unknown "
                             f"fleet keys {sorted(unknown)}")
        for k in ("cache_capacity", "canaries", "max_queue_depth",
                  "vnodes", "keys", "user_window", "sweep_requests"):
            if int(fl[k]) <= 0:
                raise ValueError(f"soak scenario {sc['name']!r}: "
                                 f"fleet.{k} must be positive, got {fl[k]}")
        if not 0.0 <= float(fl["locality"]) <= 1.0:
            raise ValueError(f"soak scenario {sc['name']!r}: "
                             "fleet.locality must be in [0, 1]")
        if not fl["fleet_sizes"] \
                or any(int(s) <= 0 for s in fl["fleet_sizes"]):
            raise ValueError(f"soak scenario {sc['name']!r}: "
                             "fleet.fleet_sizes must be positive ints")
        sc["fleet"] = fl
    if sc["knobs"] is not None:
        # scenario knob overrides name REGISTRY knobs with legal values
        # (ISSUE 18) — an override outside the tune registry is a typo
        # or an untracked knob, both of which must refuse at load (the
        # same rule tools/lint_invariants.py lints the checked-in
        # scenario files with)
        from distributed_embeddings_tpu.tune import registry as _tune_reg
        if not isinstance(sc["knobs"], dict):
            raise ValueError(f"soak scenario {sc['name']!r}: 'knobs' "
                             "must be an env -> value object")
        for env_name, value in sc["knobs"].items():
            err = _tune_reg.validate_override(env_name, value)
            if err is not None:
                raise ValueError(
                    f"soak scenario {sc['name']!r}: knobs: {err}")
    if sc["fault_plan"] is not None:
        from distributed_embeddings_tpu import faults
        faults.FaultPlan.from_json(sc["fault_plan"])   # spec validation
    return sc


def _scenario_knob_env(scenario: dict):
    """Context manager applying a scenario's validated ``knobs`` env
    overrides for the duration of the run (restored afterwards — a soak
    must not leak its knob choices into the next mode in-process)."""
    import contextlib

    @contextlib.contextmanager
    def _cm():
        knobs = scenario.get("knobs") or {}
        prev = {k: os.environ.get(k) for k in knobs}
        os.environ.update(knobs)
        try:
            yield
        finally:
            for k, p in prev.items():
                if p is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = p
    return _cm()


class _SoakTraffic:
    """Deterministic scenario traffic: zipf-ranked ids per table with
    phase-scripted drift (alpha changes, universe rotation) and
    flash-crowd bursts. One instance per role (trainer / serving fleet),
    each on its own seeded RandomState."""

    def __init__(self, scenario: dict, universe: int, key_base: int, rng):
        self.sc = scenario
        self.universe = int(universe)
        self.key_base = int(key_base)
        self.rng = rng
        self.phases = scenario.get("traffic") or [{}]
        self._probs = {}

    def _prob(self, alpha: float):
        p = self._probs.get(alpha)
        if p is None:
            ranks = np.arange(1, self.universe + 1, dtype=np.float64)
            p = ranks ** -float(alpha)
            p /= p.sum()
            self._probs[alpha] = p
        return p

    def phase_at(self, frac: float) -> dict:
        for ph in self.phases:
            if frac <= float(ph.get("until_frac", 1.0)) + 1e-9:
                return ph
        return self.phases[-1]

    def ids(self, n: int, frac: float) -> np.ndarray:
        ph = self.phase_at(frac)
        alpha = float(ph.get("alpha", self.sc["alpha"]))
        ids = self.rng.choice(self.universe, size=n, p=self._prob(alpha))
        rot = int(ph.get("rotate", 0))
        if rot:
            ids = (ids + rot) % self.universe
        fc = ph.get("flash_crowd")
        if fc:
            burst = self.rng.random_sample(n) < float(fc.get("frac", 0.5))
            hot = self.rng.randint(0, max(int(fc.get("keys", 8)), 1),
                                   size=n)
            ids = np.where(burst, (rot + hot) % self.universe, ids)
        return self.key_base + ids.astype(np.int64)

    def batch(self, batch: int, hotness: int, tables: int, frac: float,
              dtype) -> tuple:
        cats = [self.ids(batch * hotness, frac)
                .reshape(batch, hotness).astype(dtype)
                for _ in range(tables)]
        num = np.zeros((batch, 1), np.float32)
        lab = self.rng.randn(batch).astype(np.float32)
        return num, cats, lab


def run_soak_bench(scenario: dict) -> dict:
    """One composed soak run (see module comment above). Returns the
    record; the acceptance gates ride as ``soak/*`` gauges on the
    default registry so tools/slo_soak.json can address them:

      * ``soak/poll_exceptions_escaped`` — exceptions that escaped
        `InferenceEngine.poll_updates` across the whole run (must be 0:
        consumer-side faults degrade, they never crash serving);
      * ``soak/quarantine_unreconciled`` — symmetric difference between
        the fleet's quarantined files and the fault plan's
        corrupt-published files (0 = every injected corruption was
        caught, nothing healthy was quarantined);
      * ``soak/orphan_tmp_unreconciled`` — |orphaned tmp files| vs
        |injected crashes| mismatch (0 = crashes leak exactly their tmp
        file, swept afterwards);
      * ``soak/parity_max_dev`` — max |publisher - replica| over every
        table after the post-fault recovery snapshot (0.0 = bit-exact).
    """
    import shutil
    import tempfile

    from distributed_embeddings_tpu import faults

    pub_dir = tempfile.mkdtemp(prefix="det_soak_")
    # degraded-entry postmortems (ISSUE 14): unless the operator already
    # pointed the dump dir somewhere, collect them next to the stream so
    # the record can reconcile them before cleanup
    pm_prev = os.environ.get("DET_OBS_POSTMORTEM_DIR")
    if pm_prev is None:
        os.environ["DET_OBS_POSTMORTEM_DIR"] = os.path.join(
            pub_dir, "postmortems")
    try:
        with _scenario_knob_env(scenario):
            return _run_soak_bench_inner(scenario, pub_dir)
    finally:
        # safety net: a failure ANYWHERE (replica construction, record
        # assembly) must not leave the adversarial plan installed
        # process-wide or the stream dir on disk — both idempotent
        # against the inner function's own mid-run cleanup
        if pm_prev is None:
            os.environ.pop("DET_OBS_POSTMORTEM_DIR", None)
        faults.set_plan(None)
        shutil.rmtree(pub_dir, ignore_errors=True)


def _run_soak_bench_inner(scenario: dict, pub_dir: str) -> dict:
    from distributed_embeddings_tpu import faults, obs, training
    from distributed_embeddings_tpu.parallel.mesh import create_mesh
    from distributed_embeddings_tpu.serving import InferenceEngine
    from distributed_embeddings_tpu.store import TableStore
    from distributed_embeddings_tpu.utils import checkpoint as ckpt_lib

    sc = scenario
    _ha = _load_hlo_audit()
    devs = jax.devices()
    world = min(int(sc["world"]), len(devs))
    if world < 2:
        return {"metric": "soak_composed", "soak_error":
                f"soak needs a multi-device mesh, have {len(devs)} "
                "device(s)", "git_sha": _git_sha()}
    mesh = create_mesh(devs[:world])
    reg = obs.default_registry()
    # fresh flight-recorder window (ISSUE 14): the soak's lineage
    # reconciliation asserts every published version has a track in the
    # ring — it must see only THIS run's events
    obs.reset_default_recorder()
    seed = int(sc["seed"])
    vm = sc["vocab_manage"]
    tables, vocab_rows = int(sc["tables"]), int(sc["vocab"])
    width, hotness = int(sc["width"]), int(sc["hotness"])
    steps, batch = int(sc["steps"]), int(sc["batch"])

    def build():
        return _ha._build_model(
            vocab_rows, width, "sum", tables=tables, mesh=mesh,
            vocab_slack=(int(vm["slack"]) if vm else 0))

    model = build()
    emb = model.embedding
    params = {"embedding": emb.init(jax.random.PRNGKey(seed))}
    pub_store = TableStore(emb, params["embedding"],
                           snapshot_every=int(sc["snapshot_every"]))
    mgr = None
    if vm:
        from distributed_embeddings_tpu.vocab import VocabManager
        mgr = VocabManager(emb,
                           admit_threshold=int(vm["admit_threshold"]),
                           decay=float(vm["decay"]))
    plan = (faults.FaultPlan.from_json(sc["fault_plan"])
            if sc["fault_plan"] else None)
    faults.set_plan(plan)
    # postmortem reconciliation is scoped to THIS run: an operator-set
    # DET_OBS_POSTMORTEM_DIR may hold artifacts from earlier runs, and a
    # stale corrupt_stream dump must not fail a healthy soak
    pm_dir = os.environ.get("DET_OBS_POSTMORTEM_DIR")
    pm_preexisting = (set(os.listdir(pm_dir))
                      if pm_dir and os.path.isdir(pm_dir) else set())

    # raw keys when vocab-managed (the manager owns the binding),
    # in-range physical ids otherwise
    universe = int(vm["key_space"]) if vm else vocab_rows
    key_base = 10 ** 8 if vm else 0
    id_dtype = np.int64 if vm else np.int32
    traffic = _SoakTraffic(sc, universe, key_base,
                           np.random.RandomState(seed))
    serve_traffic = _SoakTraffic(sc, universe, key_base,
                                 np.random.RandomState(seed + 999))

    def train_batches():
        for s in range(steps):
            yield traffic.batch(batch, hotness, tables,
                                (s + 1) / steps, id_dtype)

    # ---- replica fleet ------------------------------------------------
    # The fleet serves and polls from a fit CALLBACK (after each step's
    # sync point) rather than a competing thread: XLA:CPU's in-process
    # collectives deadlock when two threads interleave different meshed
    # programs over the same virtual devices, and single-threaded
    # dispatch also makes the fault plan's occurrence ordering — and
    # therefore the whole soak — deterministically replayable. The
    # replicas still consume MID-STREAM: deltas apply between training
    # steps, queries run against every intermediate version.
    escapes = []
    degraded_seen = set()
    replicas = [None] * int(sc["replicas"])

    def make_replica(i: int) -> InferenceEngine:
        remb = build().embedding
        rvocab = None
        if vm:
            from distributed_embeddings_tpu.vocab import VocabManager
            rvocab = VocabManager(
                remb, admit_threshold=int(vm["admit_threshold"]),
                decay=float(vm["decay"]))
        return InferenceEngine(
            remb, remb.init(jax.random.PRNGKey(seed + 100 + i)),
            vocab_manager=rvocab, registry=reg)

    lj = sc["late_join"]
    for i in range(len(replicas)):
        if lj is None or i != int(lj["replica"]):
            replicas[i] = make_replica(i)

    req_hist = reg.histogram("serve/request_seconds")
    rb = int(sc["request_batch"])

    def safe_poll(eng: InferenceEngine):
        """poll_updates NEVER raising is itself an acceptance gate —
        count anything that escapes instead of crashing the soak."""
        try:
            eng.poll_updates(pub_dir)
        except Exception as e:  # noqa: BLE001 - the gate counts these
            escapes.append(f"{type(e).__name__}: {e}"[:200])
        degraded_seen.update(eng.degraded_reasons())

    def serve_round(frac: float):
        for eng in replicas:
            if eng is None:
                continue
            for _ in range(int(sc["requests_per_round"])):
                req = [serve_traffic.ids(rb * hotness, frac)
                       .reshape(rb, hotness).astype(id_dtype)
                       for _ in range(tables)]
                t0 = time.perf_counter()
                out = eng.predict(req)
                # materialize: the latency is dispatch + execution, and
                # no serving program stays in flight when the next train
                # step's collectives dispatch
                for o in out:
                    np.asarray(o)
                req_hist.record(time.perf_counter() - t0)

    state = {"rounds": 0}
    poll_every = max(int(sc["poll_every_rounds"]), 1)
    churn_events = [dict(ev) for ev in (sc["churn"] or [])]

    class _FleetCallback:
        def on_step(self, step, p, loss):
            frac = (step + 1) / max(steps, 1)
            # scripted membership churn (ISSUE 16): a leave tears the
            # replica down mid-stream, a join (re)creates one that
            # re-anchors from the newest snapshot — same path late_join
            # takes; the recovery loop below revives left members so the
            # final parity audit still covers every index
            for ev in churn_events:
                if not ev.get("_done") and frac >= float(ev["at_frac"]):
                    ev["_done"] = True
                    i = int(ev["replica"])
                    if i >= len(replicas):
                        replicas.extend(
                            [None] * (i + 1 - len(replicas)))
                    if ev["action"] == "leave":
                        replicas[i] = None
                    elif replicas[i] is None:
                        replicas[i] = make_replica(i)
            if lj is not None and replicas[int(lj["replica"])] is None \
                    and frac >= float(lj["at_frac"]):
                # late join: a fresh replica re-anchors from the newest
                # snapshot mid-churn (the existing snapshot-fallback
                # path; its first poll applies snapshot + chained
                # deltas)
                replicas[int(lj["replica"])] = make_replica(
                    int(lj["replica"]))
            serve_round(frac)
            if state["rounds"] % poll_every == 0:
                for eng in replicas:
                    if eng is not None:
                        safe_poll(eng)
            state["rounds"] += 1

    fit_result = {}
    try:
        p, o, h = training.fit(
            model, params, train_batches(), steps=steps,
            optimizer=sc["optimizer"], lr=float(sc["lr"]),
            log_every=0, callbacks=[_FleetCallback()],
            store=pub_store, publish_every=int(sc["publish_every"]),
            publish_dir=pub_dir, vocab=mgr,
            vocab_every=(int(vm["every"]) if vm else 16),
            lookahead=int(sc["lookahead"]), registry=reg)
        fit_result["params"], fit_result["opt"] = p, o
        fit_result["history"] = h
    except Exception as e:  # noqa: BLE001 - surfaced in the record
        import traceback
        traceback.print_exc()
        fit_result["error"] = f"{type(e).__name__}: {e}"[:300]
    finally:
        # the fault window closes with the training run: recovery and
        # the final parity audit run on a healthy filesystem
        faults.set_plan(None)
    rounds = state["rounds"]

    record = {
        "metric": "soak_composed",
        "backend": devs[0].platform,
        "soak_scenario": sc["name"],
        "soak_steps": steps, "soak_batch": batch,
        "soak_tables": tables, "soak_vocab": vocab_rows,
        "soak_width": width, "soak_world": world,
        "soak_lookahead": int(sc["lookahead"]),
        "soak_vocab_managed": bool(vm),
        "soak_replicas": len(replicas),
        "soak_rounds": rounds,
        "git_sha": _git_sha(),
    }
    if "error" in fit_result:
        record["soak_error"] = fit_result["error"]
        return record
    history = fit_result["history"]

    # ---- recovery: one clean snapshot re-anchors every replica --------
    orphans = [n for n in os.listdir(pub_dir) if ".tmp" in n]
    pub_store.commit(fit_result["params"]["embedding"],
                     fit_result["opt"]["emb"])
    if mgr is not None:
        from distributed_embeddings_tpu.vocab import vocab_state_path
        mgr.save_state(vocab_state_path(pub_dir, pub_store.version),
                       full=False)
    recovery = pub_store.publish(pub_dir, force_snapshot=True)
    for i in range(len(replicas)):
        if replicas[i] is None:        # late joiner the run never reached
            replicas[i] = make_replica(i)
        safe_poll(replicas[i])
        safe_poll(replicas[i])         # second poll: drain any stragglers

    # ---- parity: bit-exact fleet at the recovered version -------------
    want = [np.asarray(w) for w in pub_store.get_weights()]
    parity = 0.0
    for eng in replicas:
        for a, b in zip(want, eng.store.get_weights()):
            if a.size:
                parity = max(parity, float(np.max(np.abs(
                    a - np.asarray(b)))))

    # ---- reconciliation against the fault plan's ledger ---------------
    injected_corrupt = set(plan.corrupted_paths("store.publish")) \
        if plan else set()
    union_quarantined = set()
    retries_total = 0
    replica_stats = []
    for eng in replicas:
        cons = eng._consumers.get(pub_dir)
        if cons is not None:
            union_quarantined |= set(cons.quarantined)
            retries_total += cons._retries_total
        st = eng.update_stats(pub_dir)
        replica_stats.append({k: st.get(k) for k in (
            "applied", "applied_deltas", "applied_snapshots", "version",
            "quarantined_files", "poll_retries",
            "staleness_versions_max", "staleness_s_max")})
    crash_fires = plan.counts(kind="crash_before_rename") if plan else 0
    swept = ckpt_lib.sweep_orphan_tmp(pub_dir)
    injected_by_kind = {}
    if plan is not None:
        for e in plan.events:
            injected_by_kind[e["kind"]] = \
                injected_by_kind.get(e["kind"], 0) + 1

    # ---- postmortem artifacts (ISSUE 14): every degraded ENTRY must
    # have dumped one, every dump must name a reason the fleet actually
    # reported — symmetric difference 0, same shape as the quarantine
    # reconciliation above
    postmortems = []
    if pm_dir and os.path.isdir(pm_dir):
        for name in sorted(set(os.listdir(pm_dir)) - pm_preexisting):
            try:
                with open(os.path.join(pm_dir, name)) as f:
                    doc = json.load(f)
                postmortems.append({
                    "file": name, "reason": doc.get("reason"),
                    "trace_events": len(doc.get("trace", {})
                                        .get("traceEvents", [])),
                    "has_snapshot": doc.get("snapshot") is not None,
                    "lineage_versions": len(doc.get(
                        "lineage_versions", []))})
            except Exception as e:  # noqa: BLE001 - a torn dump is a finding
                postmortems.append({"file": name, "error": str(e)[:150]})
    pm_reasons = {p["reason"].split(":", 1)[1] for p in postmortems
                  if str(p.get("reason", "")).startswith("degraded:")}
    pm_unreconciled = len(pm_reasons.symmetric_difference(degraded_seen)) \
        + len([p for p in postmortems if "error" in p])

    # ---- lineage reconciliation: every published (non-paused) version
    # must have an async track in the flight-recorder ring
    published = history.get("published", [])
    lineage_versions = set(obs.default_recorder().lineage_versions())
    published_versions = {i["version"] for i in published
                          if i["kind"] != "paused"}
    lineage_missing = sorted(published_versions - lineage_versions)
    summ = req_hist.summary()
    record.update({
        "soak_publishes": len([i for i in published
                               if i["kind"] != "paused"]),
        "soak_paused_publishes": len([i for i in published
                                      if i["kind"] == "paused"]),
        "soak_publish_crashes": len(history.get("publish_crashes", [])),
        "soak_recovery_version": recovery["version"],
        "soak_parity_max_dev": parity,
        "soak_injected_faults": injected_by_kind,
        "soak_injected_corrupt_files": len(injected_corrupt),
        "soak_quarantined_files": len(union_quarantined),
        "soak_quarantine_unreconciled": len(
            union_quarantined.symmetric_difference(injected_corrupt)),
        "soak_orphan_tmp_files": len(orphans),
        "soak_orphan_swept": len(swept),
        "soak_orphan_tmp_unreconciled": abs(len(orphans) - crash_fires),
        "soak_poll_exceptions_escaped": len(escapes),
        "soak_poll_escape_examples": escapes[:5],
        "soak_degraded_reasons_seen": sorted(degraded_seen),
        "soak_postmortems": postmortems,
        "soak_postmortem_reasons": sorted(pm_reasons),
        "soak_postmortem_unreconciled": pm_unreconciled,
        "soak_lineage_versions": len(lineage_versions),
        "soak_lineage_missing_published": lineage_missing,
        "soak_poll_retries_total": retries_total,
        "soak_replica_stats": replica_stats,
        "soak_serve_p50_ms": summ["p50_ms"],
        "soak_serve_p99_ms": summ["p99_ms"],
        "soak_serve_requests": summ["count"],
        "soak_fault_events": (plan.events[:50] if plan else []),
    })
    if sc["lookahead"]:
        record["soak_compile_counts"] = {
            "prefetch": reg.gauge("lookahead/compiles",
                                  stage="prefetch").value,
            "fused": reg.gauge("lookahead/compiles", stage="fused").value,
        }
    if "vocab_stats" in history:
        record["soak_vocab_stats"] = history["vocab_stats"]
    if "ingest_stages" in history:
        record["soak_ingest_bottleneck"] = max(
            history["ingest_stages"],
            key=lambda k: history["ingest_stages"][k]["mean_ms"])

    # the SLO-addressable acceptance gauges (tools/slo_soak.json)
    reg.gauge("soak/parity_max_dev").set(parity)
    reg.gauge("soak/quarantine_unreconciled").set(
        record["soak_quarantine_unreconciled"])
    reg.gauge("soak/orphan_tmp_unreconciled").set(
        record["soak_orphan_tmp_unreconciled"])
    reg.gauge("soak/poll_exceptions_escaped").set(len(escapes))
    reg.gauge("soak/postmortem_unreconciled").set(pm_unreconciled)
    reg.gauge("soak/lineage_missing_published").set(len(lineage_missing))
    return record


def soak_main(argv=None) -> int:
    """`bench.py --mode soak` entry point: one JSON line, like main()."""
    import argparse
    p = argparse.ArgumentParser(
        description="composed production soak (ROADMAP item 5)")
    p.add_argument("--mode", choices=["soak"], default="soak")
    p.add_argument("--scenario", required=True,
                   help="scenario JSON file (tools/soak_scenarios/)")
    p.add_argument("--steps", type=int, default=None,
                   help="override the scenario's step count")
    p.add_argument("--replicas", type=int, default=None,
                   help="override the scenario's replica count")
    _add_profile_arg(p)
    args = p.parse_args(argv)
    if os.environ.get("DET_BENCH_FORCE_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    try:
        scenario = load_soak_scenario(args.scenario)
        if args.steps is not None:
            scenario["steps"] = args.steps
        if args.replicas is not None:
            scenario["replicas"] = args.replicas
        if args.steps is not None or args.replicas is not None:
            # re-validate: overrides must hit the same positivity and
            # composition refusals the scenario file does (an explicit
            # --steps 0 is an error, not "no override")
            scenario = load_soak_scenario(scenario)
        _load_hlo_audit()._ensure_world(max(2, int(scenario["world"])))
        record = _run_with_device_attribution(
            lambda: run_soak_bench(scenario), args.profile)
    except Exception as e:  # noqa: BLE001 - one JSON line, like main()
        import traceback
        traceback.print_exc()
        record = {"metric": "soak_composed",
                  "soak_error": str(e)[:300], "git_sha": _git_sha()}
    trace_path = os.environ.get("DET_OBS_TRACE")
    if trace_path:
        # the run's flight-recorder window — span timeline + the
        # per-version lineage tracks — as a Perfetto-loadable artifact
        # next to the record (ISSUE 14)
        try:
            from distributed_embeddings_tpu.obs import default_recorder
            doc = default_recorder().export(trace_path)
            record["trace_export"] = {
                "path": trace_path,
                "events": len(doc["traceEvents"]),
                "dropped": doc["metadata"]["dropped_events"],
                "lineage_versions":
                    len(default_recorder().lineage_versions())}
        except Exception as e:  # noqa: BLE001 - artifact, not the record
            record["trace_export"] = {"error": str(e)[:200]}
    record = _stamp_audit_findings(record)
    try:
        # the audit result doubles as the `audit/findings` gauge so the
        # SLO rule file gates it alongside the soak gauges (the
        # obs_smoke idiom)
        from distributed_embeddings_tpu.obs import default_registry
        af = record.get("audit_findings", {})
        default_registry().gauge("audit/findings").set(
            af["count"] if isinstance(af, dict) and "count" in af else -1)
    except Exception:  # noqa: BLE001 - accounting must not kill the bench
        pass
    record = _stamp_metrics_snapshot(record)
    print(json.dumps(record))
    ok = ("soak_error" not in record
          and record.get("soak_poll_exceptions_escaped", 1) == 0
          and record.get("soak_quarantine_unreconciled", 1) == 0
          and record.get("soak_postmortem_unreconciled", 1) == 0
          and record.get("soak_parity_max_dev", 1.0) == 0.0)
    slo = record.get("slo_findings")
    if isinstance(slo, dict) and slo.get("count"):
        ok = False
    return 0 if ok else 1


# ------------------------------------------------------------- fleet mode
# (ISSUE 16) The serving fleet tier: a FleetRouter consistent-hashes
# keyed request batches over an elastic replica fleet (each replica an
# InferenceEngine + MicroBatcher with a replica= label on the shared
# registry), sheds on queue pressure with typed results, joins/leaves
# members mid-traffic, and promotes published versions fleet-wide only
# after the canaries report bit-exact parity against the publisher.
# Scenarios are the soak's JSON format plus the optional "churn" /
# "fleet" keys; tools/soak_scenarios/replica_churn.json is the
# reference adversarial run.


def run_fleet_bench(scenario: dict) -> dict:
    """One fleet-tier run. Returns the record; the acceptance gates ride
    as ``fleet/*`` gauges on the default registry so tools/slo_soak.json
    can address them:

      * ``fleet/parity_max_dev`` — max |publisher - serving replica|
        after the recovery version promotes (0.0 = bit-exact fleet);
      * ``fleet/idle_sheds`` — sheds during the single-request idle arm
        (must be 0: admission control never sheds an unloaded fleet);
      * ``fleet/replicas_unrouted`` — serving replicas owning zero
        request keys (0 = routing covers the whole rotation);
      * ``fleet/bad_version_served`` — non-canary members ever observed
        at a condemned version (0 = rollback containment held).
    """
    import shutil
    import tempfile

    from distributed_embeddings_tpu import faults

    pub_dir = tempfile.mkdtemp(prefix="det_fleet_")
    try:
        with _scenario_knob_env(scenario):
            return _run_fleet_bench_inner(scenario, pub_dir)
    finally:
        faults.set_plan(None)
        shutil.rmtree(pub_dir, ignore_errors=True)


def _run_fleet_bench_inner(scenario: dict, pub_dir: str) -> dict:
    from distributed_embeddings_tpu import faults, obs, training
    from distributed_embeddings_tpu.fleet import (AdmissionController,
                                                  FleetRouter, HashRing)
    from distributed_embeddings_tpu.parallel.mesh import create_mesh
    from distributed_embeddings_tpu.serving import InferenceEngine
    from distributed_embeddings_tpu.store import TableStore

    sc = scenario
    fl = sc["fleet"] or dict(_FLEET_DEFAULTS)
    record = {"metric": "fleet_tier", "git_sha": _git_sha()}
    if sc["vocab_manage"] is not None:
        record["fleet_error"] = ("fleet mode serves physical ids; "
                                 "vocab_manage scenarios belong to "
                                 "--mode soak")
        return record
    if int(sc["lookahead"]):
        record["fleet_error"] = (
            "fleet mode host-offloads every bucket so the HotRowCache "
            "tier is in the serve path, and lookahead>0 cannot patch "
            "offloaded lookups (the refusal training.fit raises); set "
            "lookahead: 0 in the scenario")
        return record
    _ha = _load_hlo_audit()
    devs = jax.devices()
    world = min(int(sc["world"]), len(devs))
    if world < 2:
        record["fleet_error"] = ("fleet bench needs a multi-device "
                                 f"mesh, have {len(devs)} device(s)")
        return record
    mesh = create_mesh(devs[:world])
    reg = obs.default_registry()
    obs.reset_default_recorder()
    seed = int(sc["seed"])
    tables, vocab_rows = int(sc["tables"]), int(sc["vocab"])
    width, hotness = int(sc["width"]), int(sc["hotness"])
    steps, batch = int(sc["steps"]), int(sc["batch"])
    rb = int(sc["request_batch"])
    n_keys, win = int(fl["keys"]), int(fl["user_window"])
    locality = float(fl["locality"])

    # a one-element device budget host-offloads every bucket: the
    # serving-tier memory shape (tables in host memory, HotRowCache in
    # HBM on top) — hit rate as a function of fleet size is the whole
    # point of key-affine routing, so the cache must be in the path
    gpu_budget = 1

    def build():
        return _ha._build_model(vocab_rows, width, "sum", tables=tables,
                                mesh=mesh, gpu_embedding_size=gpu_budget)

    model = build()
    emb = model.embedding
    params = {"embedding": emb.init(jax.random.PRNGKey(seed))}
    pub_store = TableStore(emb, params["embedding"],
                           snapshot_every=int(sc["snapshot_every"]))
    plan = (faults.FaultPlan.from_json(sc["fault_plan"])
            if sc["fault_plan"] else None)
    faults.set_plan(plan)

    traffic = _SoakTraffic(sc, vocab_rows, 0, np.random.RandomState(seed))

    def train_batches():
        for s in range(steps):
            yield traffic.batch(batch, hotness, tables,
                                (s + 1) / steps, np.int32)

    zipf_p = np.arange(1, vocab_rows + 1, dtype=np.float64) \
        ** -float(sc["alpha"])
    zipf_p /= zipf_p.sum()

    def keyed_request(key, rng):
        """Key-affine request content: `locality` of the ids come from
        the key's own vocab window (a user's recurring items), the rest
        from the global zipf tail — a replica that keeps seeing the
        same keys warms its cache for exactly those windows."""
        n = rb * hotness
        base = (int(key) * 2654435761) % max(vocab_rows - win, 1)
        n_local = int(round(n * locality))
        cats = []
        for _ in range(tables):
            ids = np.empty(n, np.int64)
            ids[:n_local] = base + rng.randint(0, win, size=n_local)
            ids[n_local:] = rng.choice(vocab_rows, size=n - n_local,
                                       p=zipf_p)
            rng.shuffle(ids)
            cats.append(ids.reshape(rb, hotness).astype(np.int32))
        return cats

    def reference_weights(version):
        # parity gates only when the publisher's in-memory tables ARE
        # that version; a paused publish leaves the newest on-disk
        # version behind the store's, and the verdict is health-only
        # rather than condemning a healthy file against future bytes
        if int(version) != int(pub_store.version):
            return None
        return pub_store.get_weights()

    def make_replica(i: int) -> InferenceEngine:
        remb = build().embedding
        return InferenceEngine(
            remb, remb.init(jax.random.PRNGKey(seed + 100 + i)),
            cache_capacity=int(fl["cache_capacity"]), registry=reg,
            replica=f"r{i}")

    router = FleetRouter(
        pub_dir, registry=reg, vnodes=int(fl["vnodes"]),
        canaries=int(fl["canaries"]),
        reference_weights=reference_weights,
        admission=AdmissionController(
            int(fl["max_queue_depth"]),
            None if fl["max_queue_rows"] is None
            else int(fl["max_queue_rows"])))
    for i in range(int(sc["replicas"])):
        router.add_replica(f"r{i}", make_replica(i))

    churn_events = [dict(ev) for ev in (sc["churn"] or [])]
    churn_log = []
    state = {"rounds": 0, "serve_s": 0.0}
    poll_every = max(int(sc["poll_every_rounds"]), 1)
    rpr = int(sc["requests_per_round"])
    key_rng = np.random.RandomState(seed + 555)

    class _FleetTierCallback:
        # single-threaded serve-from-fit-callback, the soak's thread
        # model: XLA:CPU collectives deadlock across threads, and one
        # dispatch order keeps the fault plan's occurrences replayable
        def on_step(self, step, p, loss):
            frac = (step + 1) / max(steps, 1)
            for ev in churn_events:
                if not ev.get("_done") and frac >= float(ev["at_frac"]):
                    ev["_done"] = True
                    i = int(ev["replica"])
                    name = f"r{i}"
                    entry = {"step": int(step), "action": ev["action"],
                             "replica": name}
                    try:
                        if ev["action"] == "leave":
                            router.remove_replica(name)
                        elif name not in router._members:
                            router.add_replica(name, make_replica(i))
                    except Exception as e:  # noqa: BLE001 - churn must not kill fit
                        entry["error"] = \
                            f"{type(e).__name__}: {e}"[:200]
                    churn_log.append(entry)
            t0 = time.perf_counter()
            n_req = rpr * max(len(router._serving()), 1)
            for _ in range(n_req):
                key = int(key_rng.randint(0, n_keys))
                router.submit(keyed_request(key, key_rng), key=key)
            router.flush()
            state["serve_s"] += time.perf_counter() - t0
            if state["rounds"] % poll_every == 0:
                router.step()
            state["rounds"] += 1

    fit_result = {}
    try:
        p, o, h = training.fit(
            model, params, train_batches(), steps=steps,
            optimizer=sc["optimizer"], lr=float(sc["lr"]),
            log_every=0, callbacks=[_FleetTierCallback()],
            store=pub_store, publish_every=int(sc["publish_every"]),
            publish_dir=pub_dir, lookahead=int(sc["lookahead"]),
            registry=reg)
        fit_result["params"], fit_result["opt"] = p, o
        fit_result["history"] = h
    except Exception as e:  # noqa: BLE001 - surfaced in the record
        import traceback
        traceback.print_exc()
        fit_result["error"] = f"{type(e).__name__}: {e}"[:300]
    finally:
        faults.set_plan(None)

    record.update({
        "backend": devs[0].platform,
        "fleet_scenario": sc["name"],
        "fleet_steps": steps, "fleet_world": world,
        "fleet_replicas_start": int(sc["replicas"]),
        "fleet_rounds": state["rounds"],
    })
    if "error" in fit_result:
        record["fleet_error"] = fit_result["error"]
        return record

    # ---- recovery: one clean snapshot, promoted through the canaries --
    pub_store.commit(fit_result["params"]["embedding"],
                     fit_result["opt"]["emb"])
    recovery = pub_store.publish(pub_dir, force_snapshot=True)
    promote_ticks = 0
    while router.pinned_version < recovery["version"] \
            and promote_ticks < 8:
        router.step()
        promote_ticks += 1
    promoted = router.pinned_version == recovery["version"]

    # ---- parity: the serving fleet is bit-exact at the promoted pin ---
    want = [np.asarray(w) for w in pub_store.get_weights()]
    serving = router._serving()
    parity = 0.0
    for m in serving:
        for a, b in zip(want, m.engine.store.get_weights()):
            if a.size:
                parity = max(parity, float(np.max(np.abs(
                    a - np.asarray(b)))))

    # ---- idle arm: an unloaded fleet never sheds -----------------------
    shed_before = router.shed
    for k in range(max(len(serving), 1)):
        router.submit(keyed_request(k, key_rng), key=k)
        router.flush()
    idle_sheds = router.shed - shed_before

    # ---- burst arm: same-key overload sheds typed, never raises --------
    shed_before = router.shed
    burst_n = 3 * int(fl["max_queue_depth"])
    burst_reasons: dict = {}
    for _ in range(burst_n):
        r = router.submit(keyed_request(7, key_rng), key=7)
        if not r:
            burst_reasons[r.shed_reason] = \
                burst_reasons.get(r.shed_reason, 0) + 1
    router.flush()
    burst_sheds = router.shed - shed_before

    # ---- routing coverage over the key space ---------------------------
    assign = router.ring.assignments(range(n_keys))
    keys_per_replica = {m.name: 0 for m in serving}
    for owner in assign.values():
        if owner in keys_per_replica:
            keys_per_replica[owner] += 1
    replicas_unrouted = sum(1 for v in keys_per_replica.values()
                            if v == 0)

    # ---- hit rate vs fleet size: fresh sub-fleets replay ONE keyed
    # stream (same seed per size) so the only variable is how many
    # replicas split the key space over the same per-replica cache.
    # Replayed twice: at the fleet's f32 storage and over int8-quantized
    # buckets (ISSUE 17 — the HotRowCache decode seam keeps the cache in
    # the serve path for quantized tables: slots hold decoded f32 rows,
    # misses decode payload x scale in the same host-compute region, and
    # serve/cache_bypassed_buckets must stay 0).
    def hit_rate_at(size: int, storage_dtype=None) -> dict:
        ring = HashRing(int(fl["vnodes"]))
        engs = {}
        for i in range(size):
            if storage_dtype is None:
                e = make_replica(900 + i)
            else:
                qemb = _ha._build_model(
                    vocab_rows, width, "sum", tables=tables, mesh=mesh,
                    gpu_embedding_size=gpu_budget,
                    storage_dtype=storage_dtype).embedding
                e = InferenceEngine(
                    qemb, qemb.init(jax.random.PRNGKey(seed + 900 + i)),
                    cache_capacity=int(fl["cache_capacity"]),
                    registry=reg, replica=f"q{i}")
            e.poll_updates(pub_dir)        # re-anchor on the recovery
            name = f"s{i}"
            ring.add(name)
            engs[name] = e
        srng = np.random.RandomState(seed + 4242)
        for _ in range(int(fl["sweep_requests"])):
            key = int(srng.randint(0, n_keys))
            out = engs[ring.route(key)].predict(keyed_request(key, srng))
            for o in out:
                np.asarray(o)
        caches = [c for e in engs.values()
                  for c in (getattr(e, "caches", {}) or {}).values()]
        hits = sum(c.hits for c in caches)
        misses = sum(c.misses for c in caches)
        return {"fleet_size": size,
                "hit_rate": round(hits / (hits + misses), 4)
                if hits + misses else 0.0}

    hit_curve = [hit_rate_at(int(s)) for s in fl["fleet_sizes"]]
    hit_curve_q = [hit_rate_at(int(s), storage_dtype="int8")
                   for s in fl["fleet_sizes"]]
    cache_bypassed = max(
        (v for k, v in reg.snapshot()["gauges"].items()
         if k.startswith("serve/cache_bypassed_buckets")), default=0.0)

    # ---- latency: per-replica histograms + the fleet-wide merge (the
    # UNLABELED serve/request_seconds family = the whole fleet, so the
    # shared "requests-served" SLO rule addresses fleet runs too)
    replica_names = sorted(
        {f"r{i}" for i in range(int(sc['replicas']))}
        | {f"r{int(ev['replica'])}" for ev in (sc["churn"] or [])})
    fleet_hist = reg.histogram("serve/request_seconds")
    per_replica = {}
    for name in replica_names:
        h = reg.histogram("serve/request_seconds", replica=name)
        if h.count:
            s = h.summary()
            per_replica[name] = {k: s[k]
                                 for k in ("count", "p50_ms", "p99_ms")}
            fleet_hist.merge(h)
    fleet_summ = fleet_hist.summary()

    stats = router.stats()
    admitted = router.submitted - router.shed
    bad_served = reg.counter("fleet/bad_version_served_total").value
    record.update({
        "fleet_routed_qps": round(admitted / state["serve_s"], 2)
        if state["serve_s"] else 0.0,
        "fleet_submitted": router.submitted,
        "fleet_shed": router.shed,
        "fleet_shed_rate": stats["shed_rate"],
        "fleet_shed_by_reason": {
            r: reg.counter("fleet/shed_total", reason=r).value
            for r in ("queue_depth", "queue_rows", "no_replicas",
                      "oversize", "router_error")
            if reg.counter("fleet/shed_total", reason=r).value},
        "fleet_serve_requests": fleet_summ["count"],
        "fleet_serve_p50_ms": fleet_summ["p50_ms"],
        "fleet_serve_p99_ms": fleet_summ["p99_ms"],
        "fleet_replica_latency": per_replica,
        "fleet_hit_rate_curve": hit_curve,
        "fleet_hit_rate_curve_quantized": hit_curve_q,
        "fleet_cache_bypassed_buckets": cache_bypassed,
        "fleet_canary_events": router.rollout.events[:50],
        "fleet_promotes": stats["promotes"],
        "fleet_rollbacks": stats["rollbacks"],
        "fleet_bad_versions": stats["bad_versions"],
        "fleet_pinned_version": stats["pinned_version"],
        "fleet_recovery_version": recovery["version"],
        "fleet_recovery_promoted": promoted,
        "fleet_parity_max_dev": parity,
        "fleet_idle_sheds": idle_sheds,
        "fleet_burst_submitted": burst_n,
        "fleet_burst_sheds": burst_sheds,
        "fleet_burst_shed_reasons": burst_reasons,
        "fleet_replicas_unrouted": replicas_unrouted,
        "fleet_keys_per_replica": keys_per_replica,
        "fleet_churn_events": churn_log,
        "fleet_bad_version_served": bad_served,
        "fleet_router_errors": stats["router_errors"],
        "fleet_router_error_examples": router.errors[:5],
        "fleet_member_stats": stats["members"],
    })

    # the SLO-addressable acceptance gauges (tools/slo_soak.json)
    reg.gauge("fleet/parity_max_dev").set(parity)
    reg.gauge("fleet/cache_bypassed_buckets").set(cache_bypassed)
    reg.gauge("fleet/idle_sheds").set(idle_sheds)
    reg.gauge("fleet/replicas_unrouted").set(replicas_unrouted)
    reg.gauge("fleet/bad_version_served").set(bad_served)
    reg.gauge("fleet/recovery_promoted").set(1 if promoted else 0)
    return record


def fleet_main(argv=None) -> int:
    """`bench.py --mode fleet` entry point: one JSON line, like main()."""
    import argparse
    p = argparse.ArgumentParser(
        description="serving fleet tier bench (ISSUE 16)")
    p.add_argument("--mode", choices=["fleet"], default="fleet")
    p.add_argument("--scenario", required=True,
                   help="scenario JSON file (tools/soak_scenarios/)")
    p.add_argument("--steps", type=int, default=None,
                   help="override the scenario's step count")
    p.add_argument("--replicas", type=int, default=None,
                   help="override the scenario's starting fleet size")
    _add_profile_arg(p)
    args = p.parse_args(argv)
    if os.environ.get("DET_BENCH_FORCE_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    try:
        scenario = load_soak_scenario(args.scenario)
        if args.steps is not None:
            scenario["steps"] = args.steps
        if args.replicas is not None:
            scenario["replicas"] = args.replicas
        if args.steps is not None or args.replicas is not None:
            scenario = load_soak_scenario(scenario)
        _load_hlo_audit()._ensure_world(max(2, int(scenario["world"])))
        record = _run_with_device_attribution(
            lambda: run_fleet_bench(scenario), args.profile)
    except Exception as e:  # noqa: BLE001 - one JSON line, like main()
        import traceback
        traceback.print_exc()
        record = {"metric": "fleet_tier",
                  "fleet_error": str(e)[:300], "git_sha": _git_sha()}
    trace_path = os.environ.get("DET_OBS_TRACE")
    if trace_path:
        try:
            from distributed_embeddings_tpu.obs import default_recorder
            doc = default_recorder().export(trace_path)
            record["trace_export"] = {
                "path": trace_path,
                "events": len(doc["traceEvents"]),
                "dropped": doc["metadata"]["dropped_events"]}
        except Exception as e:  # noqa: BLE001 - artifact, not the record
            record["trace_export"] = {"error": str(e)[:200]}
    record = _stamp_audit_findings(record)
    try:
        # the audit result doubles as the `audit/findings` gauge so the
        # SLO rule file gates it alongside the fleet gauges (the
        # obs_smoke idiom)
        from distributed_embeddings_tpu.obs import default_registry
        af = record.get("audit_findings", {})
        default_registry().gauge("audit/findings").set(
            af["count"] if isinstance(af, dict) and "count" in af else -1)
    except Exception:  # noqa: BLE001 - accounting must not kill the bench
        pass
    record = _stamp_metrics_snapshot(record)
    print(json.dumps(record))
    ok = ("fleet_error" not in record
          and record.get("fleet_idle_sheds", 1) == 0
          and record.get("fleet_replicas_unrouted", 1) == 0
          and record.get("fleet_bad_version_served", 1) == 0
          and record.get("fleet_recovery_promoted") is True
          and record.get("fleet_parity_max_dev", 1.0) == 0.0)
    slo = record.get("slo_findings")
    if isinstance(slo, dict) and slo.get("count"):
        ok = False
    return 0 if ok else 1


# ---------------------------------------------------------------- roofline
# v5e per-chip peaks (public spec); used only for the efficiency estimate.
# ------------------------------------------------------------------ tune
# Attribution-driven auto-tuner (ISSUE 18): search the registry's knob
# space on a named workload, prune the cross-product with the existing
# STATIC cost models (every pruned arm logged with its predicted costs
# and a rationale — no silent caps), measure the survivors with the
# timing method of record, and emit a tools/tuned/<workload>.json
# config-of-record the `tune.resolve` seam consumes. The winner adopts
# only parity-EXACT knob values (registry classes); bounded-parity
# values (bf16 wire, quantized storage) ride as staged_tpu_arms for a
# human + tunnel-window decision, mirroring _maybe_write_measured_
# defaults's standing refusals.

TUNE_WORKLOADS = {
    # the DLRM-ish shape every wire/kernels bench anchors on
    "dlrm": dict(vocab=100_000, width=128, tables=8, batch=8192,
                 hotness=1, world=8, iters=5),
    # CI-sized: small enough to trace + measure on 2 virtual CPU devices
    "tiny": dict(vocab=512, width=16, tables=2, batch=64,
                 hotness=1, world=2, iters=3),
}

# The offline search space: CPU-measurable arms over registry knobs.
# dedup_impl is deliberately ABSENT (parity=numerics — never
# auto-flipped); pallas scatter/lookup arms stay with --mode kernels
# until a TPU number exists (compile-probe gated dispatch would make a
# CPU "measurement" of them vacuous).
TUNE_SEARCH_SPACE = {
    "DET_EXCHANGE_WIRE": ["f32", "bf16", "bf16-sr"],
    "DET_ID_WIRE": ["auto", "int32"],
    "DET_SCATTER_IMPL": ["xla", "tiled"],
}


def _tune_env(overrides: dict):
    """Apply one arm's env overrides, restoring on exit (the run_ab_arm
    idiom; an empty-string value means 'unset')."""
    import contextlib

    @contextlib.contextmanager
    def _cm():
        prev = {k: os.environ.get(k) for k in overrides}
        for k, v in overrides.items():
            if v == "":
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        try:
            yield
        finally:
            for k, p in prev.items():
                if p is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = p
    return _cm()


def run_tune_bench(workload: str, shape: dict, survivors: int = 4,
                   optimizer: str = "adagrad", seed: int = 0) -> dict:
    """One tune search over TUNE_SEARCH_SPACE at `shape`.

    Stages: enumerate (registry-validated cross-product) -> prune
    (static cost models: `expected_collective_bytes` +
    `exchange_padding_report`, lexicographic; full pruned log + ordering
    audit) -> measure survivors (`_slope_time_scan`, shared weights/
    data; per-arm warm-loss parity vs the defaults arm rides as
    evidence) -> select (structurally cheapest measured arm, measured
    time breaking ties) -> split winner into adoptable (parity-exact)
    vs staged (parity-bounded) -> assemble the validated
    tuned-config-v1 record. The winner CONFIG (adoptable values only)
    is itself measured if no survivor arm equals it, so `beats_default`
    always compares measured against measured."""
    from distributed_embeddings_tpu.analysis.programs import (
        expected_collective_bytes)
    from distributed_embeddings_tpu.parallel.mesh import create_mesh
    from distributed_embeddings_tpu.tune import registry as tune_registry
    from distributed_embeddings_tpu.tune import search as tune_search

    _isolate_from_measured_defaults()
    devs = jax.devices()
    world = min(shape["world"], len(devs))
    record = {
        "metric": "tune_search", "workload": workload,
        "backend": devs[0].platform, "git_sha": _git_sha(),
        "tune_shape": dict(shape, world=world),
        "tune_optimizer": optimizer, "tune_seed": seed,
        "tune_space": {k: list(v) for k, v in TUNE_SEARCH_SPACE.items()},
    }
    if world < 2:
        record["tune_error"] = (
            f"tune needs a multi-device mesh, have {len(devs)} "
            "device(s) — the wire knobs have no exchange at world 1")
        return record
    mesh = create_mesh(devs[:world])
    _ha = _load_hlo_audit()
    hot = [shape["hotness"]] * shape["tables"]

    def build_model():
        # no explicit exchange_wire/... args: every knob resolves from
        # the arm's env through the tune.resolve seam, exactly as a
        # production run would read it
        return _ha._build_model(shape["vocab"], shape["width"], "sum",
                                tables=shape["tables"], mesh=mesh)

    arms = tune_search.enumerate_arms(TUNE_SEARCH_SPACE)
    record["tune_arms_enumerated"] = len(arms)

    predicted = {}

    def cost_fn(arm):
        if arm.key in predicted:
            return predicted[arm.key]
        with _tune_env(arm.overrides):
            emb = build_model().embedding
            by_dtype = expected_collective_bytes(
                emb, hot, shape["batch"], train=True)
            rep = emb.exchange_padding_report(hotness=hot)
        predicted[arm.key] = {
            "collective_bytes": float(sum(by_dtype.values())),
            "padding_ratio": float(rep["ratio"]),
        }
        return predicted[arm.key]

    prune_order = ("collective_bytes", "padding_ratio")
    kept, pruned_log, audit_ok = tune_search.prune_by_cost(
        arms, cost_fn, keep=survivors, order=prune_order)
    for p in pruned_log:
        print(f"tune: pruned {p['arm']}: {p['rationale']}",
              file=sys.stderr)
    print(f"tune: {len(kept)} survivor(s) of {len(arms)} arms "
          f"(prune audit {'ok' if audit_ok else 'FAILED'})",
          file=sys.stderr)

    # shared data across every arm (the A/B discipline: identical
    # batches + init seed, so losses differ only by the arm's knobs)
    rng = np.random.RandomState(seed)
    nb = 2
    batch, vocab, tables = shape["batch"], shape["vocab"], shape["tables"]
    data = [
        (np.zeros((batch, 1), np.float32),
         tuple(rng.randint(0, vocab, size=(batch, shape["hotness"]))
               .astype(np.int32) for _ in range(tables)),
         rng.randn(batch).astype(np.float32))
        for _ in range(nb)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs),
                           *[(jnp.asarray(n), tuple(map(jnp.asarray, c)),
                              jnp.asarray(l)) for (n, c, l) in data])

    warms = {}

    def measure(arm, extra_tags=None):
        entry = {"key": arm.key, "overrides": dict(arm.overrides),
                 "predicted": dict(cost_fn(arm))}
        entry.update(extra_tags or {})
        try:
            with _tune_env(arm.overrides):
                model = build_model()
                emb = model.embedding
                params = {"embedding": emb.init(jax.random.PRNGKey(seed))}
                init_fn, step_fn = make_sparse_train_step(
                    model, optimizer, lr=0.01)
                opt_state = init_fn(params)
                dt, warm, raw = _slope_time_scan(
                    step_fn, params, opt_state, stacked, nb,
                    shape["iters"], span_path=f"bench/tune/{arm.key}")
            entry["step_ms"] = round(dt * 1e3, 3)
            entry["raw"] = raw
            warms[arm.key] = warm
        except Exception as e:  # noqa: BLE001 - an arm never kills the run
            entry["error"] = str(e)[:200]
        return entry

    measured = [measure(a) for a in kept]
    ok = [m for m in measured if "step_ms" in m]
    if not ok or not any(m["key"] == "defaults" for m in ok):
        record["tune_error"] = (
            "no measurable survivor arms (the defaults baseline must "
            "always measure): "
            + "; ".join(f"{m['key']}: {m.get('error')}" for m in measured))
        record["tune_pruned"] = pruned_log
        return record

    # per-arm warm-loss parity vs the defaults arm — measured evidence
    # next to the registry's parity CLASS (exact values are additionally
    # guarded by the repo's standing parity gates)
    base_warm = warms["defaults"]
    for m in measured:
        w = warms.get(m["key"])
        if w is not None:
            n = min(len(w), len(base_warm))
            m["loss_max_dev_vs_defaults"] = float(
                np.max(np.abs(w[:n] - base_warm[:n])))

    def rank(m):
        c = m["predicted"]
        return (tuple(float(c.get(k, 0.0)) for k in prune_order),
                m["step_ms"])

    best = min(ok, key=rank)
    adoptable, staged = tune_search.split_adoptable(best["overrides"])
    # the winner CONFIG: adoptable values, bounded values reverted to
    # their registry fallback (they ride below as staged arms instead)
    winner_full = {
        env: adoptable.get(env, tune_registry.get_knob(env).fallback)
        for env in TUNE_SEARCH_SPACE}
    winner = {env: v for env, v in adoptable.items()
              if v != tune_registry.get_knob(env).fallback}
    win_arm = tune_search.Arm(dict(winner_full))
    win_entry = next((m for m in ok if m["overrides"] == winner_full),
                     None)
    if win_entry is None:
        win_entry = measure(win_arm, {"winner_config": True})
        measured.append(win_entry)
        if "step_ms" not in win_entry:
            record["tune_error"] = ("winner config failed to measure: "
                                    + str(win_entry.get("error")))
            record["tune_pruned"] = pruned_log
            return record
        w = warms.get(win_entry["key"])
        if w is not None:
            n = min(len(w), len(base_warm))
            win_entry["loss_max_dev_vs_defaults"] = float(
                np.max(np.abs(w[:n] - base_warm[:n])))

    base_entry = next(m for m in ok if m["key"] == "defaults")
    # adoption rail: the winner CONFIG must measure at least as fast as
    # the hand-picked defaults (within slope-timing noise) or adoption
    # reverts to the defaults — "match or beat", never a measured
    # regression shipped on a structural prediction alone
    if "step_ms" in win_entry \
            and win_entry["step_ms"] > base_entry["step_ms"] * 1.10:
        record["tune_winner_reverted"] = {
            "candidate": dict(winner),
            "candidate_step_ms": win_entry["step_ms"],
            "defaults_step_ms": base_entry["step_ms"],
            "reason": "candidate config measured slower than the "
                      "defaults baseline beyond the 10% noise "
                      "tolerance — adoption reverted to defaults",
        }
        winner, winner_full = {}, {
            env: tune_registry.get_knob(env).fallback
            for env in TUNE_SEARCH_SPACE}
        win_entry = base_entry
    base_cost, win_cost = base_entry["predicted"], win_entry["predicted"]
    beats_default = {
        # structural metrics are the claim of record (slope timings on
        # a loaded CI host carry noise; the 10% tolerance below is
        # advisory evidence, not a gate)
        "collective_bytes": (win_cost["collective_bytes"]
                             <= base_cost["collective_bytes"]),
        "padding_ratio": (win_cost["padding_ratio"]
                          <= base_cost["padding_ratio"]),
        "step_ms_within_noise": (win_entry["step_ms"]
                                 <= base_entry["step_ms"] * 1.10),
    }

    staged_tpu_arms = []
    for m in ok:
        _ad, st = tune_search.split_adoptable(m["overrides"])
        if not st:
            continue
        staged_tpu_arms.append({
            "arm": m["key"], "staged_overrides": st,
            "step_ms": m["step_ms"], "predicted": m["predicted"],
            "loss_max_dev_vs_defaults": m.get("loss_max_dev_vs_defaults"),
            "reason": ("parity=bounded values never auto-adopt: a TPU "
                       "tunnel-window decision with --profile evidence "
                       "promotes them (docs/perf_model.md 'Tuning')"),
        })

    import time as _time
    doc = tune_search.build_record(
        workload=workload, winner=winner, arms=measured,
        pruned=pruned_log, prune_order=prune_order,
        prune_audit_ok=audit_ok, beats_default=beats_default,
        staged_tpu_arms=staged_tpu_arms, git_sha=_git_sha(),
        backend=devs[0].platform,
        created_at=_time.strftime("%Y-%m-%dT%H:%M:%SZ", _time.gmtime()),
        extra={"shape": dict(shape, world=world),
               "optimizer": optimizer, "seed": seed,
               "space": {k: list(v) for k, v in
                         TUNE_SEARCH_SPACE.items()}})
    record["tuned_record"] = doc
    record["tune_winner"] = winner
    record["tune_beats_default"] = beats_default
    record["tune_prune_audit_ok"] = audit_ok
    record["tune_measured_arms"] = sum(1 for m in measured
                                       if "step_ms" in m)
    record["tune_pruned_count"] = len(pruned_log)
    return record


def tune_main(argv=None) -> int:
    """`bench.py --mode tune` entry point: one JSON line, like main(),
    plus the tools/tuned/<workload>.json config-of-record on success."""
    import argparse
    p = argparse.ArgumentParser(description="attribution-driven knob "
                                            "auto-tuner")
    p.add_argument("--mode", choices=["tune"], default="tune")
    p.add_argument("--workload", default="dlrm",
                   choices=sorted(TUNE_WORKLOADS))
    for dim in ("vocab", "width", "tables", "batch", "hotness", "world",
                "iters"):
        p.add_argument(f"--{dim}", type=int, default=None,
                       help=f"override the workload's {dim}")
    p.add_argument("--survivors", type=int, default=4,
                   help="measured arms kept by the cost-model prune "
                        "(the defaults baseline always survives)")
    p.add_argument("--optimizer", default="adagrad",
                   choices=["sgd", "adagrad", "adam"])
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default=None,
                   help="directory for the config-of-record (default "
                        "tools/tuned/ next to this script; --rehearse "
                        "defaults to a scratch dir instead)")
    p.add_argument("--rehearse", action="store_true",
                   help="rehearsal run (tools/window_rehearsal.py): "
                        "tiny shapes, scratch output dir unless --out, "
                        "record marked rehearsal=true")
    _add_profile_arg(p)
    args = p.parse_args(argv)
    if os.environ.get("DET_BENCH_FORCE_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    shape = dict(TUNE_WORKLOADS["tiny" if args.rehearse
                                else args.workload])
    for dim in shape:
        v = getattr(args, dim, None)
        if v is not None:
            shape[dim] = v
    _load_hlo_audit()._ensure_world(max(2, shape["world"]))
    try:
        record = _run_with_device_attribution(
            lambda: run_tune_bench(
                args.workload, shape, survivors=args.survivors,
                optimizer=args.optimizer, seed=args.seed),
            args.profile)
    except Exception as e:  # noqa: BLE001 - one JSON line, like main()
        import traceback
        traceback.print_exc()
        record = {"metric": "tune_search", "workload": args.workload,
                  "tune_error": str(e)[:300], "git_sha": _git_sha()}
    if args.rehearse:
        record["rehearsal"] = True
    doc = record.get("tuned_record")
    if doc is not None:
        # the --profile attribution is part of the evidence trail: copy
        # it into the config-of-record before writing
        if "device_attribution" in record:
            doc["device_attribution"] = record["device_attribution"]
        if args.out:
            out_dir = args.out
        elif args.rehearse:
            import tempfile
            out_dir = tempfile.mkdtemp(prefix="det_tune_rehearsal_")
        else:
            out_dir = os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "tools",
                "tuned")
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir, f"{args.workload}.json")
        with open(path + ".tmp", "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(path + ".tmp", path)
        record["tuned_path"] = path
        print(f"tune: config-of-record written to {path}",
              file=sys.stderr)
    print(json.dumps(_stamp_metrics_snapshot(_stamp_audit_findings(record))))
    return 0 if "tune_error" not in record else 1


HBM_GBPS = {"v5e": 819.0, "v5p": 2765.0, "v4": 1228.0}
BF16_TFLOPS = {"v5e": 197.0, "v5p": 459.0, "v4": 275.0}


def _chip_gen(device) -> str:
    kind = (getattr(device, "device_kind", "") or "").lower()
    for gen in ("v5e", "v5p", "v4"):
        if gen in kind:
            return gen
    import os
    return os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")


def dlrm_roofline_bytes_flops(table_widths, hotness, mlp_dims, dtype_bytes=4):
    """Per-sample HBM bytes (embedding path) and MLP flops for one train step.

    Embedding tables are HBM-bandwidth bound: fwd row gather (1 read), bwd
    scatter-add (read+write), and the fused optimizer update touching param +
    accumulator (2 reads + 2 writes) — 7 row-transfers per looked-up row
    is the optimistic lower bound the kernel should approach.
    """
    emb_bytes = sum(7 * w * h * dtype_bytes
                    for w, h in zip(table_widths, hotness))
    flops = 0
    for a, b in zip(mlp_dims[:-1], mlp_dims[1:]):
        flops += 2 * a * b
    return emb_bytes, 3 * flops  # fwd + 2x bwd matmuls


def run_dlrm_bench(batches=(65536, 32768, 16384), iters=20):
    """Single-chip DLRM at Criteo-Kaggle scale (26 x 100k x 128 one-hot
    tables — the 'criteo' synthetic config): samples/sec + roofline estimate.
    Reference 8xA100 Criteo-1TB: 9.16M samples/s TF32 => 1.14M/GPU
    (examples/dlrm/README.md:7)."""
    if os.environ.get("DET_BENCH_FORCE_CPU") == "1":
        batches, iters = (256,), 4
    cfg = SYNTHETIC_MODELS["criteo"]
    model = SyntheticModel(cfg, mesh=None, distributed=True)
    last_err = None
    for batch in batches:
        try:
            dt = run_at_batch(model, batch, iters=iters)
        except Exception as e:  # noqa: BLE001
            if not _is_oom(e):
                raise
            last_err = str(e)[:300]
            e.__traceback__ = None
            del e
            continue
        extra = {"dlrm_timing_raw": getattr(run_at_batch, "last_raw", None),
                 "dlrm_ab_sort_ms": round(dt * 1e3, 3)}
        # aggregation-impl A/B (round-3/4 scatter data): cumsum removes the
        # segment-sum + rep-build scatters; dense trades a [V, w] temp for
        # promise-free updates; tiled replaces the whole chain with the
        # one-hot-matmul kernel. Winner reported.
        if (jax.devices()[0].platform != "cpu"
                and os.environ.get("DET_BENCH_AB", "1") == "1"):
            from distributed_embeddings_tpu.ops import sparse_update
            extra["dlrm_best_path"] = "sort"
            arms = [
                ("dlrm_ab_cumsum", {"DET_DEDUP_IMPL": "cumsum"},
                 None, "cumsum"),
                # the criteo bucket (333M elems) auto-picks sort; measure
                # dense explicitly by raising the auto threshold
                ("dlrm_ab_dense",
                 {"DET_SPARSE_DENSE_MAX": str(500 * 1024 * 1024)},
                 None, "dense"),
                ("dlrm_ab_tiled", {"DET_SCATTER_IMPL": "tiled"},
                 sparse_update.prevalidate_tiled, "tiled-onehot-matmul"),
                ("dlrm_ab_tiled_full",
                 {"DET_SCATTER_IMPL": "tiled", "DET_LOOKUP_PATH": "tiled"},
                 sparse_update.prevalidate_tiled, "tiled-fwd+bwd"),
            ]
            for key, env, validate, label in arms:
                dt_arm = run_ab_arm(extra, key, env, cfg, batch, iters,
                                    validate=validate)
                if dt_arm is not None and dt_arm < dt:
                    dt = dt_arm
                    extra["dlrm_best_path"] = label
                    extra["dlrm_timing_raw"] = extra.get(f"{key}_raw")
        dev = jax.devices()[0]
        gen = _chip_gen(dev)
        widths, hot = [], []
        for ec in cfg.embedding_configs:
            for _ in range(ec.num_tables):
                widths.extend([ec.width] * len(ec.nnz))
                hot.extend(ec.nnz)
        mlp = ([sum(widths) + cfg.num_numerical_features]
               + list(cfg.mlp_sizes) + [1])
        emb_bytes, mlp_flops = dlrm_roofline_bytes_flops(widths, hot, mlp)
        bound_s = max(batch * emb_bytes / (HBM_GBPS[gen] * 1e9),
                      batch * mlp_flops / (BF16_TFLOPS[gen] * 1e12))
        return {
            "dlrm_batch": batch,
            "dlrm_step_ms": round(dt * 1e3, 3),
            "dlrm_samples_per_sec": round(batch / dt),
            "dlrm_roofline_step_ms": round(bound_s * 1e3, 3),
            "dlrm_roofline_frac": round(bound_s / dt, 3),
            # reference DLRM: 9.16M samples/s on 8xA100 TF32 => 1.145M/GPU
            # (examples/dlrm/README.md:7); per-chip normalized comparison
            "dlrm_vs_ref_per_chip": round(batch / dt / 1_144_734, 3),
            **extra,
        }
    return {"dlrm_error": last_err or "all batches failed"}


def supervise() -> int:
    """Run the whole bench as a killable subprocess with retries.

    Round-2 postmortem, part 2: the claim can wedge BETWEEN a successful
    probe and the in-process init (observed on hardware: probe ran a matmul,
    the next process's jax.devices() hung forever). A probe alone therefore
    cannot make the bench hang-proof — the entire measurement runs in a
    subprocess that we can kill and retry, and only the JSON line crosses
    back.
    """
    import subprocess
    # the claim watcher holds /tmp/det_tpu_busy while its own serialized
    # measurement stages run; two processes fighting over the single chip
    # claim is how the tunnel wedges, so wait (bounded) for it to clear.
    # The watcher's own bench stage skips this via DET_BENCH_SKIP_BUSY_WAIT.
    if os.environ.get("DET_BENCH_SKIP_BUSY_WAIT") != "1":
        def _busy_holder_alive():
            """The lock file carries the watcher's pid; a stale lock (dead
            holder, e.g. SIGKILLed watcher skipping its EXIT trap) must not
            delay the bench."""
            try:
                with open("/tmp/det_tpu_busy") as f:
                    pid = int(f.read().strip() or "0")
                return pid > 0 and os.path.exists(f"/proc/{pid}")
            except (OSError, ValueError):
                return False
        waited = 0.0
        while _busy_holder_alive() and waited < float(
                os.environ.get("DET_BENCH_BUSY_WAIT_S", 3600)):
            if waited == 0:
                print("waiting for claim-watcher stages to finish "
                      "(/tmp/det_tpu_busy)", file=sys.stderr, flush=True)
            time.sleep(15)
            waited += 15
    attempts = int(os.environ.get("DET_BENCH_ATTEMPTS", 3))
    per_try_s = float(os.environ.get("DET_BENCH_TRY_TIMEOUT_S", 3300))
    backoff_s = float(os.environ.get("DET_BENCH_BACKOFF_S", 120))
    env = dict(os.environ, DET_BENCH_INNER="1")
    # persistent compile cache: an attempt killed mid-measurement leaves its
    # compiles behind for the retry (tunnel compiles are the slow part)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_det_tpu")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    last = ""
    for i in range(attempts):
        try:
            p = subprocess.run([sys.executable, "-u", __file__],
                               capture_output=True, text=True,
                               timeout=per_try_s, env=env)
        except subprocess.TimeoutExpired as e:
            last = f"attempt {i + 1}: timed out after {per_try_s:.0f}s " \
                   "(wedged tunnel claim?)"
            if e.stderr:
                err = e.stderr
                sys.stderr.write(err.decode("utf-8", "replace")[-1500:]
                                 if isinstance(err, bytes) else err[-1500:])
            print(last, file=sys.stderr, flush=True)
            # the backoff matters MOST here: a killed claim needs time to
            # release before the next attempt re-claims
            if i + 1 < attempts:
                time.sleep(backoff_s)
            continue
        sys.stderr.write(p.stderr[-2000:])
        json_line = None
        for ln in p.stdout.splitlines():
            if ln.startswith("{"):
                json_line = ln
        if p.returncode == 0 and json_line:
            print(json_line)
            return 0
        last = (f"attempt {i + 1}: rc={p.returncode} "
                f"{(p.stderr or p.stdout)[-300:]}")
        print(last, file=sys.stderr, flush=True)
        if i + 1 < attempts:
            time.sleep(backoff_s)
    print(f"bench failed after {attempts} attempts: {last}", file=sys.stderr)
    print(_outage_evidence(), file=sys.stderr, flush=True)
    return 1


def _outage_evidence() -> str:
    """Summarize the background claim watcher's probe history (if present)
    so a failed BENCH artifact documents the outage, not just the symptom."""
    import glob
    paths = sorted(glob.glob("/tmp/claim_watch*.log"), key=os.path.getmtime)
    lines = []
    if paths:
        # newest log only: older rounds' watchers must not be conflated
        # with the current outage
        try:
            with open(paths[-1]) as f:
                lines = [ln.strip() for ln in f
                         if "attempt" in ln or "probe" in ln
                         or "claim OK" in ln or "SUCCESS" in ln]
        except OSError:
            pass
    if not lines:
        return "(no claim-watcher history available)"
    fails = sum("failed" in ln for ln in lines)
    older = (f" ({len(paths) - 1} older watcher log(s) not counted)"
             if len(paths) > 1 else "")
    return (f"claim-watcher history [{paths[-1].rsplit('/', 1)[-1]}]: "
            f"{fails} failed probes, first={lines[0]!r} "
            f"last={lines[-1]!r}{older} — TPU tunnel claim wedged "
            "(jax.devices() hangs; see docs/round2_notes.md and "
            "TPU_OUTAGE_r03.log)")


_LAST_TPU_RECORD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "tools", "bench_last_tpu.json")


def _emit_cached_record(reason: str) -> bool:
    """The axon tunnel claim wedges for hours at a time (rounds 1-3); when
    it is down at bench time but a real hardware measurement landed earlier
    in the round, emit that record EXPLICITLY MARKED as cached rather than
    returning only an error artifact. The marker keeps it honest; the
    measured_at timestamp says when the chip actually answered."""
    try:
        with open(_LAST_TPU_RECORD) as f:
            record = json.load(f)
    except (OSError, ValueError):
        return False
    record["cached"] = True
    record["cached_reason"] = reason[:200]
    # attributability (ISSUE 4 satellite): a cached replay must carry BOTH
    # shas — the one the chip measured (git_sha, "unknown" for pre-field
    # records like BENCH_r05's) and the HEAD that emitted the replay, so
    # the artifact is traceable even when the measurement predates the
    # git_sha field
    record.setdefault("git_sha", "unknown")
    record["cached_emitted_at_sha"] = _git_sha()
    # staleness: a cached record measured at sha X no longer describes HEAD
    # when perf-relevant files changed since (VERDICT r3 item 4)
    measured_sha = record.get("git_sha", "")
    if measured_sha and measured_sha != "unknown":
        changed = _perf_files_changed_since(measured_sha)
        if changed < 0:
            record["cached_stale"] = True
            record["cached_stale_reason"] = (
                f"could not diff measured sha {measured_sha[:12]} against "
                "HEAD (git unavailable or sha unknown)")
        elif changed:
            record["cached_stale"] = True
            record["cached_stale_reason"] = (
                f"{changed} perf-relevant files (ops/layers/training) "
                f"changed between measured sha {measured_sha[:12]} and HEAD")
    else:
        record["cached_stale"] = True
        record["cached_stale_reason"] = "cached record predates git_sha field"
    print(json.dumps(record))
    return True


def main():
    _isolate_from_measured_defaults()
    if os.environ.get("DET_BENCH_FORCE_CPU") == "1":
        # plumbing validation without a chip: tiny batches, cpu platform
        # (sitecustomize pre-selects the TPU plugin, so force post-import)
        jax.config.update("jax_platforms", "cpu")
        devices = jax.devices()
    else:
        try:
            devices = _init_backend_with_retry()
        except RuntimeError as e:
            if _emit_cached_record(f"tunnel down at bench time: {e}"):
                return
            raise
    print(f"backend: {devices[0].platform} x{len(devices)} "
          f"({devices[0].device_kind})", file=sys.stderr, flush=True)

    cfg = SYNTHETIC_MODELS["tiny"]
    model = SyntheticModel(cfg, mesh=None, distributed=True)
    # the reference chip (A100) has 80G; fall back by batch until we fit
    last_err = None
    batch_ladder = (65536, 32768, 16384, 8192)
    if os.environ.get("DET_BENCH_FORCE_CPU") == "1":
        batch_ladder = (256,)
    for batch in batch_ladder:
        try:
            dt = run_at_batch(model, batch)
        except Exception as e:  # noqa: BLE001
            if not _is_oom(e):
                raise
            # drop the traceback so the failed attempt's device buffers are
            # freed before the smaller-batch retry
            last_err = str(e)[:500]
            e.__traceback__ = None
            del e
            print(f"batch {batch} OOM, retrying smaller",
                  file=sys.stderr, flush=True)
            continue
        dt_ms = dt * 1e3
        throughput = batch / dt
        baseline_throughput = BASELINE_BATCH / (BASELINE_TINY_1GPU_MS / 1e3)
        record = {
            "metric": f"synthetic_tiny_step_time_batch{batch}_adagrad_1chip",
            "value": round(dt_ms, 3),
            "unit": "ms",
            "vs_baseline": round(throughput / baseline_throughput, 3),
            "tiny_timing_raw": getattr(run_at_batch, "last_raw", None),
            "git_sha": _git_sha(),
        }
        try:
            from distributed_embeddings_tpu.models.synthetic import (
                expand_embedding_configs)
            specs, tmap, hot = expand_embedding_configs(cfg)
            widths = [specs[t][1] for t in tmap]
            mlp = ([sum(widths) + cfg.num_numerical_features]
                   + list(cfg.mlp_sizes) + [1])
            emb_b, mlp_f = dlrm_roofline_bytes_flops(widths, hot, mlp)
            gen_name = _chip_gen(jax.devices()[0])
            bound_s = max(batch * emb_b / (HBM_GBPS[gen_name] * 1e9),
                          batch * mlp_f / (BF16_TFLOPS[gen_name] * 1e12))
            record["tiny_roofline_step_ms"] = round(bound_s * 1e3, 3)
            record["tiny_roofline_frac"] = round(bound_s / dt, 3)
            stats = getattr(jax.devices()[0], "memory_stats", lambda: None)()
            if stats and stats.get("peak_bytes_in_use"):
                record["hbm_peak_gib"] = round(
                    stats["peak_bytes_in_use"] / 2**30, 2)
        except Exception:  # noqa: BLE001 - never lose the primary metric
            pass
        # sort-count fingerprint of the step being timed (ISSUE 2): lowering
        # only (no compile), so it is tunnel-safe; a perf regression on
        # hardware can then be attributed to (or cleared of) a re-sort
        # regression from the same record
        try:
            _ha = _load_hlo_audit()
            record["hlo_sort_audit"] = [
                _ha.audit_tapped_step(strategy="sort"),
                _ha.audit_tapped_step(strategy="tiled",
                                      lookup_path="tiled"),
            ]
        except Exception as e:  # noqa: BLE001 - audit must not kill bench
            record["hlo_sort_audit_error"] = str(e)[:200]
        # lookup-path A/B (round-2 verdict item 2): tiny's widths (8/16)
        # are sub-lane, so the default path falls back to XLA gathers; the
        # contender is the forced Pallas path with the narrow-width DMA
        # kernel (self-validated per (width, dtype) on first compiled
        # use). Both arms are recorded; the headline takes the winner.
        if (jax.devices()[0].platform != "cpu"
                and os.environ.get("DET_BENCH_AB", "1") == "1"):
            try:
                os.environ["DET_LOOKUP_PATH"] = "pallas"
                os.environ["DET_PALLAS_NARROW"] = "1"
                # hardware-validate the narrow DMA path EAGERLY (it cannot
                # run under the traced forward); unvalidated widths fall
                # back to XLA inside the trace
                from distributed_embeddings_tpu.ops import pallas_lookup
                record["tiny_ab_narrow_validated"] = {
                    str(k): v for k, v in
                    pallas_lookup.prevalidate_narrow((8, 16, 32, 64)).items()}
                dt_p = run_at_batch(
                    SyntheticModel(cfg, mesh=None, distributed=True), batch)
                ab_raw = getattr(run_at_batch, "last_raw", None)
                record["tiny_ab_default_ms"] = round(dt_ms, 3)
                record["tiny_ab_pallas_ms"] = round(dt_p * 1e3, 3)
                # honest labeling: when no narrow width validated, the
                # "pallas" arm ran the XLA fallback for every narrow bucket
                # and the two arms differ only in the small-vocab one-hot
                # kernel routing
                narrow_any = any(record.get("tiny_ab_narrow_validated",
                                            {}).values())
                ab_label = ("pallas+narrow" if narrow_any
                            else "pallas(narrow fell back to xla)")
                if dt_p < dt:
                    record["value"] = round(dt_p * 1e3, 3)
                    record["vs_baseline"] = round(
                        (batch / dt_p) / baseline_throughput, 3)
                    record["tiny_best_path"] = ab_label
                    record["tiny_timing_raw"] = ab_raw
                    # keep companion metrics consistent with the winner
                    if "tiny_roofline_step_ms" in record:
                        record["tiny_roofline_frac"] = round(
                            record["tiny_roofline_step_ms"]
                            / record["value"], 3)
                    stats = getattr(jax.devices()[0], "memory_stats",
                                    lambda: None)()
                    if stats and stats.get("peak_bytes_in_use"):
                        # process-wide peak across both arms
                        record["hbm_peak_gib"] = round(
                            stats["peak_bytes_in_use"] / 2**30, 2)
                else:
                    record["tiny_best_path"] = "default(xla)"
            except Exception as e:  # noqa: BLE001 - A/B must not kill bench
                record["tiny_ab_error"] = str(e)[:200]
            finally:
                os.environ.pop("DET_LOOKUP_PATH", None)
                os.environ.pop("DET_PALLAS_NARROW", None)
            # remaining arms (round-3/4 scatter-bottleneck responses), each
            # through the shared runner; winner takes the headline.
            from distributed_embeddings_tpu.ops import sparse_update
            arms = [
                # scatter-free cumsum dedup
                ("tiny_ab_cumsum", {"DET_DEDUP_IMPL": "cumsum"},
                 None, "xla+cumsum-dedup"),
                # per-row DMA RMW scatter (round 3; gated on hardware
                # validation — r03 toolchain rejected all DMA kernels;
                # 'pallas' now names the fused deduped-row strategy, the
                # DMA family moved to 'pallas-dma')
                ("tiny_ab_pallas_scatter",
                 {"DET_SCATTER_IMPL": "pallas-dma"},
                 sparse_update.prevalidate_pallas_scatter,
                 "pallas-rmw-scatter"),
                # ISSUE 12 fused sparse path: exact dedup + one tile-walk
                # RMW stream per bucket (gated per (backend, width class))
                ("tiny_ab_pallas_fused", {"DET_SCATTER_IMPL": "pallas"},
                 lambda: sparse_update.prevalidate_pallas_fused(16),
                 "pallas-fused-rows"),
                # fully fused: gather->combine forward + fused update
                ("tiny_ab_pallas_fused_full",
                 {"DET_SCATTER_IMPL": "pallas",
                  "DET_LOOKUP_PATH": "fused"},
                 lambda: sparse_update.prevalidate_pallas_fused(16),
                 "pallas-fused-fwd+bwd"),
                # round-4 tiled one-hot-matmul kernels: BlockSpec streams
                # only, aggregation on the MXU (ops/pallas_tiled.py)
                ("tiny_ab_tiled", {"DET_SCATTER_IMPL": "tiled"},
                 sparse_update.prevalidate_tiled, "tiled-onehot-matmul"),
                # forward gather through the tiled kernel as well
                ("tiny_ab_tiled_full",
                 {"DET_SCATTER_IMPL": "tiled", "DET_LOOKUP_PATH": "tiled"},
                 sparse_update.prevalidate_tiled, "tiled-fwd+bwd"),
            ]
            for key, env, validate, label in arms:
                dt_arm = run_ab_arm(record, key, env, cfg, batch, 10,
                                    validate=validate)
                if dt_arm is not None and dt_arm * 1e3 < record["value"]:
                    record["value"] = round(dt_arm * 1e3, 3)
                    record["vs_baseline"] = round(
                        (batch / dt_arm) / baseline_throughput, 3)
                    record["tiny_best_path"] = label
                    record["tiny_timing_raw"] = record.get(f"{key}_raw")
                    if "tiny_roofline_step_ms" in record:
                        record["tiny_roofline_frac"] = round(
                            record["tiny_roofline_step_ms"]
                            / record["value"], 3)
        # secondary workload: DLRM samples/sec + HBM roofline (north-star
        # metric, BASELINE.json) — carried in the same single JSON line
        try:
            record.update(run_dlrm_bench())
        except Exception as e:  # noqa: BLE001 - never lose the primary metric
            record["dlrm_error"] = str(e)[:300]
        try:
            _maybe_write_measured_defaults(record)
        except Exception as e:  # noqa: BLE001 - self-tuning must not kill it
            record["measured_defaults_error"] = str(e)[:200]
        print(json.dumps(_stamp_metrics_snapshot(_stamp_audit_findings(record))))
        if jax.devices()[0].platform != "cpu":
            try:
                record["measured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                      time.gmtime())
                with open(_LAST_TPU_RECORD, "w") as f:
                    json.dump(record, f)
            except OSError:
                pass
        return
    raise SystemExit(f"all batch sizes OOM'd: {last_err}")


def _cli_mode() -> str:
    for i, arg in enumerate(sys.argv):
        if arg == "--mode" and i + 1 < len(sys.argv):
            return sys.argv[i + 1]
        if arg.startswith("--mode="):
            return arg.split("=", 1)[1]
    return "train"


if __name__ == "__main__":
    if _cli_mode() == "serve":
        sys.exit(serve_main(sys.argv[1:]))
    elif _cli_mode() == "ingest":
        sys.exit(ingest_main(sys.argv[1:]))
    elif _cli_mode() == "hotrows":
        sys.exit(hotrows_main(sys.argv[1:]))
    elif _cli_mode() == "wire":
        sys.exit(wire_main(sys.argv[1:]))
    elif _cli_mode() == "vocab":
        sys.exit(vocab_main(sys.argv[1:]))
    elif _cli_mode() == "lookahead":
        sys.exit(lookahead_main(sys.argv[1:]))
    elif _cli_mode() == "kernels":
        sys.exit(kernels_main(sys.argv[1:]))
    elif _cli_mode() == "soak":
        sys.exit(soak_main(sys.argv[1:]))
    elif _cli_mode() == "fleet":
        sys.exit(fleet_main(sys.argv[1:]))
    elif _cli_mode() == "storedtype":
        sys.exit(storedtype_main(sys.argv[1:]))
    elif _cli_mode() == "tune":
        sys.exit(tune_main(sys.argv[1:]))
    elif os.environ.get("DET_BENCH_INNER") == "1":
        main()
    else:
        sys.exit(supervise())
