"""Benchmark driver: synthetic 'tiny' model training step time on one chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "ms", "vs_baseline": N}

Baseline: the reference's published single-GPU (A100-80GB) step time for the
synthetic Tiny model, global batch 65536, Adagrad: 24.433 ms
(BASELINE.md / reference examples/benchmarks/synthetic_models/README.md:69).
vs_baseline > 1 means faster than the reference.
"""

import json
import time

import numpy as np
import jax
import jax.numpy as jnp
import optax

from distributed_embeddings_tpu.models.synthetic import (
    SYNTHETIC_MODELS, SyntheticModel, InputGenerator)

BASELINE_TINY_1GPU_MS = 24.433


def main():
    cfg = SYNTHETIC_MODELS["tiny"]
    batch = 65536
    model = SyntheticModel(cfg, mesh=None, distributed=True)

    params = model.init(jax.random.PRNGKey(0))
    opt = optax.adagrad(0.01)
    opt_state = opt.init(params)

    gen = InputGenerator(cfg, batch, alpha=1.05, num_batches=4, seed=0)

    @jax.jit
    def train_step(params, opt_state, numerical, cats, labels):
        loss, grads = jax.value_and_grad(model.loss_fn)(
            params, numerical, cats, labels)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    # warmup / compile
    numerical, cats, labels = gen[0]
    params, opt_state, loss = train_step(params, opt_state, numerical, cats,
                                         labels)
    jax.block_until_ready(loss)

    iters = 20
    t0 = time.perf_counter()
    for i in range(iters):
        numerical, cats, labels = gen[i % len(gen)]
        params, opt_state, loss = train_step(params, opt_state, numerical,
                                             cats, labels)
    jax.block_until_ready(loss)
    dt_ms = (time.perf_counter() - t0) / iters * 1e3

    print(json.dumps({
        "metric": "synthetic_tiny_step_time_batch65536_adagrad_1chip",
        "value": round(dt_ms, 3),
        "unit": "ms",
        "vs_baseline": round(BASELINE_TINY_1GPU_MS / dt_ms, 3),
    }))


if __name__ == "__main__":
    main()
